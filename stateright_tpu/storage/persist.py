"""Warm-start persistence: the disk AOT executable store and the
finished-run seed store (ISSUE 19, ROADMAP item 3).

Two independent planes share this module because they share the same
discipline — **refuse, never mis-execute**:

- **``AotDiskStore``** — a content-addressed store of serialized XLA
  executables under ``CheckService(service_dir=)/aot/``. Entries are
  keyed by the checker's full AOT trace signature (the exact tuple that
  keys the in-memory ``shared_aot_cache``) plus the per-dispatch shape
  key, and every artifact carries a *fence* — jax version, backend,
  device kind/count — verified on load. A fence mismatch or a torn/
  corrupt pickle is counted (``aot_cache.refused_stale`` /
  ``aot_cache.refused_corrupt``) and treated as a miss: the caller
  recompiles, it never runs a stale binary. Unlike jax's own persistent
  compilation cache (``utils/compile_cache.py``, keyed on HLO inside
  ``jit.__call__``), this store round-trips *AOT* ``Compiled`` objects
  (``jax.experimental.serialize_executable``), so the checkers' explicit
  ``lower().compile()`` sites — the attribution engine's compile
  detectors — can skip the compile entirely: a disk hit records **zero**
  compile phase, which is the whole point.

- **``SeedStore``** — finished-run seeds under ``service_dir/seeds/``:
  the run's completion checkpoint with its visited keys rewritten as
  sorted ``FingerprintRun``s (+ per-run Bloom filters — the PR 5/PR 17
  codec), keyed by a *model-structure signature* derived from the packed
  model (per-action jaxpr digests + property/boundary/fingerprint
  digests + the init/params digest). On resubmission of a compatible
  model the service attaches the seed as ``resume_from=``: the tiered
  store's L1 is loaded from the runs (CRC-validated per run — the
  O(verify) cost), the L0 insert set is empty, the frontier queue is
  empty, and the run completes without exploring. The structural diff
  admits exactly one edit class beyond bit-identity — removal of
  provably dead actions (coverage ``fired == 0`` in the seeding run,
  every surviving digest unchanged) — and anything else falls back to a
  full recheck, so soundness never depends on the diff.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from contextlib import contextmanager
from typing import List, Optional, Tuple

import numpy as np

from ..telemetry.metrics import metrics_registry
from ..utils.faults import fault_point
from .runs import FingerprintRun

__all__ = [
    "AotDiskStore",
    "AotDiskBinding",
    "aot_fence",
    "SeedStore",
    "model_structure_signature",
    "build_seed_artifact",
    "seed_compatibility",
    "adapt_seed_checkpoint",
]

AOT_FORMAT = "stateright-aotx-v1"
SEED_FORMAT = "stateright-warmstart-seed-v1"


def _digest(*parts) -> str:
    h = hashlib.blake2b(digest_size=16)
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x1f")
    return h.hexdigest()


def _atomic_write_bytes(path: str, blob: bytes) -> None:
    """tmp + rename in the artifact's directory — a torn write leaves a
    stray tmp file, never a half-length artifact under the final name."""
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


# ---------------------------------------------------------------------------
# Disk AOT executable store
# ---------------------------------------------------------------------------


def aot_fence() -> dict:
    """The environment key an AOT artifact is only valid under. XLA
    serialized executables are not portable across jax versions,
    backends, or device topologies — a mismatch must refuse the entry,
    never deserialize-and-hope."""
    import jax

    try:
        devs = jax.devices()
        kind = devs[0].device_kind if devs else "none"
        count = len(devs)
    except Exception:
        kind, count = "unknown", 0
    return {
        "format": AOT_FORMAT,
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": kind,
        "device_count": count,
    }


class AotDiskStore:
    """Content-addressed serialized-executable files under ``root``.

    The store itself is namespace/signature-agnostic; checkers attach
    through :meth:`binding`, which closes over their cache namespace +
    full trace signature and counts hits/misses/refusals into the run's
    metrics registry (``aot_cache.*`` family)."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._fence = None

    def fence(self) -> dict:
        if self._fence is None:
            self._fence = aot_fence()
        return self._fence

    def entry_path(self, namespace, signature, kind: str, key) -> str:
        return os.path.join(
            self.root, f"{_digest(namespace, signature, kind, key)}.aotx"
        )

    def load_entry(
        self, namespace, signature, kind: str, key
    ) -> Tuple[Optional[object], str]:
        """``(executable, outcome)`` — outcome in ``{"hit", "miss",
        "stale", "corrupt"}``. Anything but a verified fence match is a
        miss variant; the artifact is never executed on doubt."""
        path = self.entry_path(namespace, signature, kind, key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None, "miss"
        try:
            entry = pickle.loads(blob)
            if not isinstance(entry, dict) or entry.get("format") != AOT_FORMAT:
                return None, "corrupt"
        except Exception:
            return None, "corrupt"
        if entry.get("fence") != self.fence():
            return None, "stale"
        try:
            from jax.experimental import serialize_executable

            exe = serialize_executable.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"]
            )
        except Exception:
            # Same-fence deserialization failure: an XLA build drift the
            # fence could not see. Refuse as stale, recompile.
            return None, "stale"
        return exe, "hit"

    def save_entry(self, namespace, signature, kind: str, key, exe) -> bool:
        """Best-effort persist (a full disk must never fail a run).

        The blob is round-trip verified before it is written: XLA
        executables that were themselves loaded from jax's persistent
        compilation cache serialize without their symbol payloads
        (deserialize fails with "Symbols not found" on this jax line),
        and persisting one would poison every later cold-process load
        into a stale-refusal + recompile. Refusing the save keeps the
        store all-loadable; the cold process just sees a plain miss."""
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(exe)
            serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree
            )
            blob = pickle.dumps(
                {
                    "format": AOT_FORMAT,
                    "fence": self.fence(),
                    "namespace": namespace,
                    "kind": kind,
                    "payload": payload,
                    "in_tree": in_tree,
                    "out_tree": out_tree,
                },
                protocol=pickle.HIGHEST_PROTOCOL,
            )
            _atomic_write_bytes(
                self.entry_path(namespace, signature, kind, key), blob
            )
            return True
        except Exception:
            return False

    def binding(self, namespace, signature, registry=None) -> "AotDiskBinding":
        return AotDiskBinding(self, namespace, signature, registry=registry)


class AotDiskBinding:
    """One checker's view of the store: namespace + trace signature
    fixed, per-outcome counters in the run's registry (the ``aot_cache``
    metric family ``bench.py --service`` reads back per job)."""

    def __init__(self, store: AotDiskStore, namespace, signature,
                 registry=None):
        reg = registry if registry is not None else metrics_registry()
        self._store = store
        self._namespace = namespace
        self._signature = signature
        self.disk_hit = reg.counter("aot_cache.disk_hit")
        self.disk_miss = reg.counter("aot_cache.disk_miss")
        self.refused_stale = reg.counter("aot_cache.refused_stale")
        self.refused_corrupt = reg.counter("aot_cache.refused_corrupt")
        self.saved = reg.counter("aot_cache.saved")
        self.save_refused = reg.counter("aot_cache.save_refused")
        self._known = set()  # keys confirmed present on disk

    def ensure(self, kind: str, key, exe) -> None:
        """Backfills the disk tier for an executable served from the
        in-memory shared cache: a warm process must still leave
        artifacts a cold process can reuse. At most one existence probe
        per key per binding, and no counter churn on the common
        already-persisted path."""
        k = (kind, key)
        if k in self._known:
            return
        self._known.add(k)
        if os.path.exists(
            self._store.entry_path(self._namespace, self._signature,
                                   kind, key)
        ):
            return
        self.save(kind, key, exe)

    def load(self, kind: str, key):
        exe, outcome = self._store.load_entry(
            self._namespace, self._signature, kind, key
        )
        if outcome == "hit":
            self.disk_hit.inc()
            self._known.add((kind, key))
        elif outcome == "stale":
            self.refused_stale.inc()
        elif outcome == "corrupt":
            self.refused_corrupt.inc()
        else:
            self.disk_miss.inc()
        return exe

    def save(self, kind: str, key, exe) -> bool:
        ok = self._store.save_entry(
            self._namespace, self._signature, kind, key, exe
        )
        if ok:
            self.saved.inc()
            self._known.add((kind, key))
        else:
            # Unserializable executable or IO failure — either way the
            # artifact is not on disk; count it so bench/report readers
            # can tell "nothing saved" from "nothing to save".
            self.save_refused.inc()
        return ok


# ---------------------------------------------------------------------------
# Model-structure signature (the seed key + the diff's evidence)
# ---------------------------------------------------------------------------


def _jaxpr_digest(fn, *protos) -> str:
    """Digest of a traced function's jaxpr *and* its closed-over
    constants — the jaxpr printer elides large literals (shape/dtype
    only), so two models differing only in a constant table would
    otherwise collide."""
    import jax

    closed = jax.make_jaxpr(fn)(*protos)
    h = hashlib.blake2b(digest_size=16)
    h.update(str(closed.jaxpr).encode())
    for c in closed.consts:
        arr = np.asarray(c)
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _state_prototype(model):
    """One unbatched abstract state (``ShapeDtypeStruct`` pytree) from
    the stacked init batch, plus a digest of the init *values* (the
    params half of the signature: two configurations with identical
    transition structure but different initial contents must not share
    seeds)."""
    import jax

    init = model.packed_init_states()
    leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(init)]
    h = hashlib.blake2b(digest_size=16)
    for arr in leaves:
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    proto = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.asarray(x).shape[1:],
                                       np.asarray(x).dtype),
        init,
    )
    return proto, h.hexdigest()


@contextmanager
def _concrete_switch():
    """Routes ``lax.switch`` calls whose index is still a *concrete*
    array straight to the selected branch for the duration of the
    signature trace. jax's own short-circuit cannot fire inside
    ``make_jaxpr`` — its dtype-convert/clamp prelude stages the index
    into a tracer before the concreteness check — so without this, every
    per-action trace would embed ALL branches and any single-branch edit
    would perturb every digest. Models that pass the raw action id to
    ``lax.switch`` get genuinely per-branch digests; models that
    arithmetic on the id first (staging it) fall through to the original
    switch and land on the conservative full-recheck path, which is the
    sound direction to degrade."""
    import jax

    orig = jax.lax.switch

    def switch(index, branches, *operands, **kwargs):
        try:
            from jax._src.core import is_concrete

            concrete = not kwargs and is_concrete(index)
        except Exception:  # noqa: BLE001 - shortcut is best-effort
            concrete = False
        if concrete:
            i = max(0, min(len(branches) - 1, int(index)))
            return branches[i](*operands)
        return orig(index, branches, *operands, **kwargs)

    jax.lax.switch = switch
    try:
        yield
    finally:
        jax.lax.switch = orig


def model_structure_signature(model) -> dict:
    """The ``(model-structure, params)`` signature incremental
    re-checking keys on. Per-action digests trace ``packed_step`` with a
    *concrete* action id — models that dispatch through ``lax.switch``
    on the raw id trace only that action's branch (see
    ``_concrete_switch``), so an edit to one action perturbs one digest;
    models that blend the id arithmetically perturb every digest and
    land on the conservative full-recheck path, which is the sound
    direction to fail in."""
    from ..ops.fingerprint import FP_SCHEME
    from ..telemetry.coverage import coverage_action_labels

    import jax.numpy as jnp

    proto, init_digest = _state_prototype(model)
    A = int(model.packed_action_count())
    with _concrete_switch():
        actions = [
            _jaxpr_digest(
                lambda s, _a=jnp.asarray(a, jnp.int32): (
                    model.packed_step(s, _a)
                ),
                proto,
            )
            for a in range(A)
        ]
    conditions = model.packed_conditions()
    properties = [
        [p.name, str(p.expectation), _jaxpr_digest(cond, proto)]
        for p, cond in zip(model.properties(), conditions)
    ]
    boundary = _jaxpr_digest(model.packed_within_boundary, proto)
    fingerprint = _jaxpr_digest(model.packed_fingerprint, proto)
    sig = {
        "format": 1,
        "fp_scheme": FP_SCHEME,
        "model": type(model).__name__,
        "action_count": A,
        "labels": coverage_action_labels(model, A),
        "init": init_digest,
        "actions": actions,
        "properties": properties,
        "boundary": boundary,
        "fingerprint": fingerprint,
    }
    sig["digest"] = _digest(
        sig["fp_scheme"], sig["model"], sig["init"], sig["actions"],
        sig["properties"], sig["boundary"], sig["fingerprint"],
    )
    # The lookup key must survive the edits the diff can judge, so it
    # excludes actions/properties: same class + same init + same
    # fingerprint scheme → same seed file.
    sig["family"] = _digest(sig["fp_scheme"], sig["model"], sig["init"])
    return sig


# ---------------------------------------------------------------------------
# Seed build / compatibility / adaptation
# ---------------------------------------------------------------------------


def build_seed_artifact(structure: dict, payload: dict,
                        coverage: Optional[dict] = None,
                        spawn_sig: Optional[str] = None) -> dict:
    """A finished run's checkpoint payload rewritten for reseeding: all
    visited keys (device L0 + any evicted tiers) merged into ONE sorted
    ``FingerprintRun`` riding the payload's ``storage`` slot, so a
    restore probes the run (Bloom + CRC — O(verify)) and inserts
    nothing, and the empty ``chunks`` queue completes immediately."""
    if payload.get("chunks"):
        raise ValueError(
            "seed artifacts require a completed run (non-empty frontier "
            "queue means the verdict is not final)"
        )
    keys = (
        payload["keys"] if payload.get("symmetry") else payload["children"]
    )
    parts = [np.asarray(keys, np.uint64).ravel()]
    storage = payload.get("storage") or {}
    for st in list(storage.get("l1", ())) + list(storage.get("l2", ())):
        parts.append(FingerprintRun.from_state(st).decode_all())
    merged = np.unique(np.concatenate(parts))
    ckpt = dict(payload)
    ckpt["chunks"] = []
    ckpt["storage"] = {
        "seq": 0,
        "l1": [FingerprintRun.build(merged).to_state()] if len(merged) else [],
        "l2": [],
    }
    actions_meta = None
    table = ((coverage or {}).get("actions") or {}).get("table")
    if table is not None:
        actions_meta = [
            {
                "label": label,
                "fired": int((table.get(label) or {}).get("fired", 0)),
                "fresh": int((table.get(label) or {}).get("fresh", 0)),
            }
            for label in structure.get("labels", [])
        ]
    return {
        "format": SEED_FORMAT,
        "structure": structure,
        "spawn_sig": spawn_sig,
        "actions_meta": actions_meta,
        "checkpoint": ckpt,
        "counts": {
            "unique": int(payload.get("unique_count", 0)),
            "states": int(payload.get("state_count", 0)),
            "max_depth": int(payload.get("max_depth", 0)),
            "runs": len(ckpt["storage"]["l1"]),
            "keys": int(len(merged)),
        },
    }


def seed_compatibility(artifact: dict, structure: dict) -> dict:
    """Judges whether ``artifact`` (a stored seed) may seed a run of the
    model described by ``structure``. Returns ``{compatible, mode,
    reason, invalidated_uniques, removed}``.

    Admitted modes:

    - ``exact`` — structure digests identical (the unchanged-model
      resubmit).
    - ``dead_action_removal`` — the new action list is the old one with
      some actions deleted, every survivor's digest unchanged (in
      order), properties/boundary/fingerprint/init unchanged, and every
      deleted action has ``fired == 0`` in the seeding run's coverage
      table: the action never produced a candidate anywhere in the
      reachable space, so deleting it provably leaves the state space,
      the counts, and every verdict untouched. The inverted
      action→uniques attribution (``fresh``) is exactly the count of
      states the edit would invalidate — the proof obligation is that
      it is zero.

    Everything else is ``compatible: False`` with the reason; the caller
    must fall back to a full recheck.
    """
    old = artifact.get("structure") or {}
    if old.get("format") != structure.get("format"):
        return _verdict(False, "format", "signature format mismatch")
    if old.get("digest") == structure.get("digest"):
        return _verdict(True, "exact", None)
    for field in ("fp_scheme", "model", "init", "boundary", "fingerprint"):
        if old.get(field) != structure.get(field):
            return _verdict(
                False, "full",
                f"{field} changed; no sound incremental reuse",
            )
    if old.get("properties") != structure.get("properties"):
        return _verdict(
            False, "full",
            "property set changed; prior verdicts do not transfer",
        )
    meta = artifact.get("actions_meta")
    if not meta:
        return _verdict(
            False, "full",
            "seeding run recorded no coverage; cannot prove removed "
            "actions dead (resubmit with coverage to enable the "
            "action-diff path)",
        )
    old_actions = list(old.get("actions", ()))
    new_actions = list(structure.get("actions", ()))
    if len(new_actions) > len(old_actions):
        return _verdict(
            False, "full", "actions added; new transitions may reach "
            "states the seed never explored",
        )
    # Align: each old action either matches the next new digest or was
    # removed. Any digest drift → not provable → full recheck.
    removed: List[int] = []
    j = 0
    for i, d in enumerate(old_actions):
        if j < len(new_actions) and new_actions[j] == d:
            j += 1
        else:
            removed.append(i)
    if j != len(new_actions):
        return _verdict(
            False, "full",
            "action bodies changed; the edit is not a pure removal of "
            "unchanged actions",
        )
    invalidated = 0
    for i in removed:
        row = meta[i] if i < len(meta) else None
        if row is None:
            return _verdict(
                False, "full",
                f"removed action {i} has no coverage row",
            )
        if row.get("fired", 1) != 0:
            # The inverted attribution: this action's uniques (and
            # their descendants) are the affected states. Non-zero ⇒
            # not provably dead ⇒ full recheck.
            invalidated = int(row.get("fresh", 0))
            return _verdict(
                False, "full",
                f"removed action {i} ({row.get('label')}) fired "
                f"{row.get('fired')} times in the seeding run "
                f"({row.get('fresh')} uniques attributed); removal is "
                "not provably dead",
                invalidated=invalidated, removed=removed,
            )
    return _verdict(
        True, "dead_action_removal", None, invalidated=0, removed=removed
    )


def _verdict(compatible, mode, reason, invalidated=0, removed=()):
    return {
        "compatible": bool(compatible),
        "mode": mode,
        "reason": reason,
        "invalidated_uniques": int(invalidated),
        "removed": list(removed),
    }


def adapt_seed_checkpoint(artifact: dict, model) -> dict:
    """The seed's checkpoint payload re-headered for ``model`` — needed
    on the dead-action-removal path, where the packed-model digest
    (action count) legitimately changed. Only call after
    ``seed_compatibility`` admitted the pair; the rewrite is what the
    compatibility proof licenses."""
    from ..checker.tpu import packed_model_digest

    ckpt = dict(artifact["checkpoint"])
    ckpt["model"] = type(model).__name__
    ckpt["model_digest"] = packed_model_digest(
        model, int(model.packed_action_count())
    )
    return ckpt


# ---------------------------------------------------------------------------
# Seed store
# ---------------------------------------------------------------------------


class SeedStore:
    """Finished-run seeds under ``root`` (``service_dir/seeds/``), one
    file per model family, atomically replaced on each completed run.
    ``load`` validates everything it will hand to a restore — format,
    pickle integrity, every FingerprintRun's CRC — and refuses with a
    reason instead of letting a torn artifact reach the checker."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    def path_for(self, family: str) -> str:
        return os.path.join(self.root, f"{family}.seed")

    def save(self, artifact: dict) -> Optional[str]:
        family = (artifact.get("structure") or {}).get("family")
        if not family:
            return None
        path = self.path_for(family)
        try:
            _atomic_write_bytes(
                path, pickle.dumps(artifact, protocol=pickle.HIGHEST_PROTOCOL)
            )
        except OSError:
            return None
        return path

    def load(self, family: str) -> Tuple[Optional[dict], Optional[str]]:
        """``(artifact, refusal_reason)`` — exactly one is None."""
        path = self.path_for(family)
        if not os.path.exists(path):
            return None, "no seed for this model family"
        try:
            # Injection seam: a torn read / failing disk surfaces here,
            # before any artifact content is trusted.
            fault_point("warmstart.seed_load")
            with open(path, "rb") as f:
                artifact = pickle.load(f)
            if (
                not isinstance(artifact, dict)
                or artifact.get("format") != SEED_FORMAT
            ):
                return None, "unrecognized seed format"
            storage = (artifact.get("checkpoint") or {}).get("storage") or {}
            for st in list(storage.get("l1", ())) + list(
                storage.get("l2", ())
            ):
                # The O(verify) half: every run's structure + CRC checks
                # here; corruption refuses the seed, never a wrong
                # visited set.
                FingerprintRun.from_state(st)
            return artifact, None
        except Exception as e:
            return None, f"seed artifact refused: {type(e).__name__}: {e}"
