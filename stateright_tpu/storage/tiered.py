"""The tiered visited-fingerprint store (host side: L1 runs + L2 spill).

``TieredVisitedStore`` owns everything below the device table: evicted
fingerprints live in delta-compressed sorted runs (``runs.py``) fronted
by per-run Bloom filters. Runs merge LSM-style once their count passes
``merge_run_threshold`` (merging also drops duplicate keys a hot
fingerprint can accumulate by re-claiming an L0 slot after eviction), and
merged bulk spills to disk files when host bytes pass ``host_budget_mib``
— the run format is identical on disk, so probes are uniform.

Probe semantics are pure membership-union: a key is visited iff it is in
the device table OR any run here. The checkers therefore stay
bit-identical to the single-tier path — each key's first global
appearance is the only one that survives the two-phase filter.

All batched numpy. Ownership under the async pipelined wave engine
(``async_pipeline=True``): every *mutation* (evict, and the merges and
spills it triggers) and every *probe* is issued from ONE thread — the
checker's host pipeline worker — in the exact order the synchronous
path would issue them, which is what keeps a probe from ever observing
an eviction submitted after it (checker/pipeline.py, the FIFO "merge
fence"). The store still carries its own reentrant lock as a second
fence: runs are immutable once built (``FingerprintRun`` never mutates
in place — merges build NEW runs and swap the tier lists), so the lock
only has to make the list swaps and the probe's run iteration atomic,
and cross-thread readers (checkpoint export at an epoch barrier, the
flight recorder's stats pull mid-crash) can never see a torn tier.
Telemetry rides a shared ``StorageInstruments`` bundle so the sharded
checker's per-shard stores aggregate into one set of gauges.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

import numpy as np

from ..telemetry import get_tracer, metrics_registry
from ..utils.faults import fault_point
from .runs import FingerprintRun

__all__ = [
    "StorageInstruments",
    "TieredVisitedStore",
    "TenantPartitions",
    "max_table_rows_for_budget",
    "validate_budget_knobs",
]


def max_table_rows_for_budget(hbm_budget_mib: float) -> int:
    """The largest power-of-two device-table capacity whose allocation
    fits ``hbm_budget_mib`` — the ONE place the table's memory layout
    (8 bytes per (hi, lo) uint32 row plus the ``MAX_PROBES`` apron,
    ``ops/hashset.py``) is priced, shared by both device checkers so a
    layout change cannot mis-size one of them."""
    from ..ops.hashset import MAX_PROBES

    budget_rows = int(hbm_budget_mib * (1 << 20)) // 8
    cap = 1
    while cap * 2 + MAX_PROBES <= budget_rows:
        cap *= 2
    return cap


def validate_budget_knobs(hbm_budget_mib, host_budget_mib, spill_dir):
    """The shared knob-consistency check: the host tiers are reachable
    only through L0 eviction, so the host knobs are meaningless without
    the HBM budget."""
    if hbm_budget_mib is None and (
        host_budget_mib is not None or spill_dir is not None
    ):
        raise ValueError(
            "host_budget_mib/spill_dir require hbm_budget_mib: "
            "without an L0 budget nothing is ever evicted to the "
            "host tiers"
        )
    if spill_dir is not None and host_budget_mib is None:
        raise ValueError(
            "spill_dir requires host_budget_mib: runs spill to disk "
            "only when the host budget overflows, so a spill_dir alone "
            "would silently never be used"
        )

# L1 runs merge into one once this many accumulate (LSM compaction): keeps
# per-probe run count bounded and reclaims cross-run duplicate space.
MERGE_RUN_THRESHOLD = 8


class StorageInstruments:
    """Counters/gauges for one checker's tiered storage, named
    ``<prefix>.storage.*``. One bundle may serve several stores (the
    sharded checker's per-shard tiers): counters accumulate across them
    and gauges are refreshed as sums over every attached store."""

    def __init__(self, prefix: str, registry=None):
        reg = registry if registry is not None else metrics_registry()
        p = f"{prefix}.storage"
        self.prefix = p
        self.evictions = reg.counter(f"{p}.evictions")
        self.evicted_fps = reg.counter(f"{p}.evicted_fps")
        self.merges = reg.counter(f"{p}.merges")
        self.spills = reg.counter(f"{p}.spills")
        self.probe_batches = reg.counter(f"{p}.probe_batches")
        self.probe_keys = reg.counter(f"{p}.probe_keys")
        self.probe_hits_l1 = reg.counter(f"{p}.probe_hits.l1")
        self.probe_hits_l2 = reg.counter(f"{p}.probe_hits.l2")
        self.blocks_decoded = reg.counter(f"{p}.blocks_decoded")
        self.bloom_rejects = reg.counter(f"{p}.bloom_rejects")
        # Bloom audit (correctness-grade observability for the
        # probabilistic machinery): per-run probes and the keys that
        # PASSED the prefilter but missed the binary search — observed
        # false positives, compared against the configured
        # ``bloom.DESIGN_FP_RATE`` (<1%) bound by the audit test.
        self.bloom_probes = reg.counter(f"{p}.host_probe.bloom_probe_total")
        self.bloom_fps = reg.counter(f"{p}.host_probe.bloom_fp_total")
        self.l0_resident = reg.gauge(f"{p}.l0_resident")
        self.l1_runs = reg.gauge(f"{p}.l1_runs")
        self.l1_fps = reg.gauge(f"{p}.l1_fps")
        self.l2_runs = reg.gauge(f"{p}.l2_runs")
        self.l2_fps = reg.gauge(f"{p}.l2_fps")
        self.host_bytes = reg.gauge(f"{p}.host_bytes")
        self.disk_bytes = reg.gauge(f"{p}.disk_bytes")
        self.compression = reg.gauge(f"{p}.compression_ratio")
        self._stores: List["TieredVisitedStore"] = []
        # Peaks (bench legs report them; gauges only carry last values).
        self.peak_l0 = 0
        self.peak_l1_fps = 0
        self.peak_l2_fps = 0
        self.peak_host_bytes = 0
        self.peak_disk_bytes = 0

    def attach(self, store: "TieredVisitedStore") -> None:
        self._stores.append(store)

    def set_l0(self, resident: int) -> None:
        self.l0_resident.set(resident)
        self.peak_l0 = max(self.peak_l0, int(resident))

    def refresh(self) -> None:
        """Re-aggregates the tier gauges over every attached store."""
        l1_runs = l1_fps = l2_runs = l2_fps = 0
        host_b = disk_b = raw_b = 0
        for s in self._stores:
            l1_runs += len(s.l1)
            l2_runs += len(s.l2)
            l1_fps += sum(r.count for r in s.l1)
            l2_fps += sum(r.count for r in s.l2)
            host_b += s.host_bytes
            disk_b += s.disk_bytes
            raw_b += 8 * sum(r.count for r in s.l1 + s.l2)
        self.l1_runs.set(l1_runs)
        self.l1_fps.set(l1_fps)
        self.l2_runs.set(l2_runs)
        self.l2_fps.set(l2_fps)
        self.host_bytes.set(host_b)
        self.disk_bytes.set(disk_b)
        stored = host_b + disk_b
        if stored:
            self.compression.set(raw_b / stored)
        self.peak_l1_fps = max(self.peak_l1_fps, l1_fps)
        self.peak_l2_fps = max(self.peak_l2_fps, l2_fps)
        self.peak_host_bytes = max(self.peak_host_bytes, host_b)
        self.peak_disk_bytes = max(self.peak_disk_bytes, disk_b)

    def bench_stats(self) -> dict:
        """The storage record a bench leg carries (BENCH_r06 trajectory)."""
        stored = (self.host_bytes.snapshot() or 0) + (
            self.disk_bytes.snapshot() or 0
        )
        raw = 8 * (
            (self.l1_fps.snapshot() or 0) + (self.l2_fps.snapshot() or 0)
        )
        return {
            "evictions": self.evictions.snapshot(),
            "evicted_fps": self.evicted_fps.snapshot(),
            "merges": self.merges.snapshot(),
            "spills": self.spills.snapshot(),
            "probe_batches": self.probe_batches.snapshot(),
            "probe_keys": self.probe_keys.snapshot(),
            "probe_hits_l1": self.probe_hits_l1.snapshot(),
            "probe_hits_l2": self.probe_hits_l2.snapshot(),
            "bloom_probe_total": self.bloom_probes.snapshot(),
            "bloom_fp_total": self.bloom_fps.snapshot(),
            "bloom_fp_rate": (
                self.bloom_fps.snapshot() / self.bloom_probes.snapshot()
                if self.bloom_probes.snapshot()
                else None
            ),
            "peak_l0_resident": self.peak_l0,
            "peak_l1_fps": self.peak_l1_fps,
            "peak_l2_fps": self.peak_l2_fps,
            "peak_host_bytes": self.peak_host_bytes,
            "peak_disk_bytes": self.peak_disk_bytes,
            "compression_ratio": (raw / stored) if stored else None,
        }


class TieredVisitedStore:
    """L1 (host runs) + L2 (disk runs) behind a batched probe/evict API.

    ``host_budget_mib`` bounds L1 payload bytes; exceeding it spills the
    largest runs to ``spill_dir`` (required alongside the budget). With
    no budget, runs stay host-resident and ``spill_dir`` is unused.
    """

    def __init__(
        self,
        host_budget_mib: Optional[float] = None,
        spill_dir: Optional[str] = None,
        merge_run_threshold: int = MERGE_RUN_THRESHOLD,
        instruments: Optional[StorageInstruments] = None,
        prefix: str = "tpu_bfs",
        shard: Optional[int] = None,
        tracer=None,
        owner=None,
    ):
        if host_budget_mib is not None and spill_dir is None:
            raise ValueError(
                "host_budget_mib needs spill_dir: exceeding the host "
                "budget spills runs to disk files"
            )
        self._host_budget = (
            None
            if host_budget_mib is None
            else int(host_budget_mib * (1 << 20))
        )
        self._spill_dir = spill_dir
        self._merge_threshold = max(2, merge_run_threshold)
        self._instr = (
            instruments
            if instruments is not None
            else StorageInstruments(prefix)
        )
        self._instr.attach(self)
        # A run-scoped tracer (checkers spawned with run_id=) stamps the
        # evict/merge/spill spans with the run id; default otherwise.
        self._tracer = tracer if tracer is not None else get_tracer()
        self._span_prefix = self._instr.prefix
        self._shard = shard
        # Fault-attribution tag (utils/faults.py): the tenant key for a
        # packed partition, None for a solo store — what lets a chaos
        # spec target exactly one tenant's host tier.
        self._owner = owner
        self._seq = 0
        # The merge fence (see the module docstring): reentrant because
        # evict() holds it across the merges/spills it triggers.
        self._fence = threading.RLock()
        self.l1: List[FingerprintRun] = []
        self.l2: List[FingerprintRun] = []

    # -- introspection -----------------------------------------------------

    @property
    def instruments(self) -> StorageInstruments:
        return self._instr

    @property
    def host_bytes(self) -> int:
        return sum(r.host_nbytes for r in self.l1 + self.l2)

    @property
    def disk_bytes(self) -> int:
        return sum(r.disk_nbytes for r in self.l2)

    @property
    def total_fps(self) -> int:
        """Stored key count (an upper bound on distinct keys until the
        next merge dedups cross-run twins)."""
        return sum(r.count for r in self.l1 + self.l2)

    def is_empty(self) -> bool:
        return not self.l1 and not self.l2

    # -- mutation ----------------------------------------------------------

    def evict(self, fps: np.ndarray) -> int:
        """Absorbs one L0 drain (u64 keys, any order, dupes allowed) as a
        new L1 run; returns the run's key count."""
        fps = np.unique(np.asarray(fps, np.uint64))
        if len(fps) == 0:
            return 0
        with self._fence, self._tracer.span(
            f"{self._span_prefix}.evict", fps=int(len(fps)),
            shard=self._shard,
        ):
            self.l1.append(FingerprintRun.build(fps))
            self._instr.evictions.inc()
            self._instr.evicted_fps.inc(int(len(fps)))
            if len(self.l1) >= self._merge_threshold:
                self._merge_l1()
            self._enforce_host_budget()
        self._instr.refresh()
        return int(len(fps))

    def _merge_l1(self) -> None:
        """LSM compaction: every L1 run merges into one sorted run (also
        deduping keys that appear in several runs)."""
        with self._tracer.span(
            f"{self._span_prefix}.merge", runs=len(self.l1),
            fps=sum(r.count for r in self.l1), shard=self._shard,
        ):
            merged = np.unique(
                np.concatenate([r.decode_all() for r in self.l1])
            )
            self.l1 = [FingerprintRun.build(merged)]
            self._instr.merges.inc()

    def _enforce_host_budget(self) -> None:
        if self._host_budget is None:
            return
        while self.host_bytes > self._host_budget and self.l1:
            # Spill the largest L1 run: biggest single relief per file.
            # Spill FIRST, then swap tiers: an ENOSPC mid-write must
            # leave the run in L1 (membership intact, retryable on the
            # next eviction), never dropped from both tiers.
            run = max(self.l1, key=lambda r: r.count)
            spilled = self._spill_run(run)
            self.l1.remove(run)
            self.l2.append(spilled)
        if len(self.l2) >= self._merge_threshold:
            self._merge_l2()

    def _merge_l2(self) -> None:
        """L2 compaction: all spill files merge into one (dedup + one
        fd + one Bloom check per probe instead of one per retired run —
        a long tight-budget run must not grow fds and probe latency
        linearly with its eviction count). The merged keys pass through
        host memory once, like every LSM compaction."""
        with self._tracer.span(
            f"{self._span_prefix}.merge", runs=len(self.l2),
            fps=sum(r.count for r in self.l2), tier="l2",
            shard=self._shard,
        ):
            merged = np.unique(
                np.concatenate([r.decode_all() for r in self.l2])
            )
            # Write the merged run BEFORE destroying its sources: a
            # spill failure here must leave every old run probeable.
            new_run = self._spill_run(FingerprintRun.build(merged))
            for r in self.l2:
                r.close()
                if r.path is not None:
                    try:
                        os.remove(r.path)
                    except OSError:
                        pass
            self.l2 = [new_run]
            self._instr.merges.inc()

    def _spill_run(self, run: FingerprintRun) -> FingerprintRun:
        # Injection seam: ENOSPC / EIO at the spill write, before any
        # tier list mutates (see _enforce_host_budget's ordering).
        fault_point("storage.spill", tenant=self._owner)
        os.makedirs(self._spill_dir, exist_ok=True)
        shard_tag = "" if self._shard is None else f"s{self._shard}_"
        path = os.path.join(
            self._spill_dir, f"{shard_tag}run{self._seq:05d}.fpr"
        )
        self._seq += 1
        with self._tracer.span(
            f"{self._span_prefix}.spill", fps=run.count,
            bytes=run.payload_nbytes, shard=self._shard,
        ):
            spilled = run.spill(path)
            self._instr.spills.inc()
        return spilled

    # -- probe -------------------------------------------------------------

    def probe(self, fps: np.ndarray) -> np.ndarray:
        """Membership mask over all runs (L1 first — newer, hotter — then
        L2). Keys already found skip the remaining runs."""
        fps = np.asarray(fps, np.uint64)
        found = np.zeros(len(fps), bool)
        if len(fps) == 0 or self.is_empty():
            return found
        # Injection seam: a real host probe can die on a torn spill
        # file, a failing disk read, or a poisoned mmap — always before
        # any result is applied, so a faulted probe never half-updates
        # the wave's verdict.
        fault_point("storage.host_probe", tenant=self._owner)
        stats: dict = {}
        hits = {"l1": 0, "l2": 0}
        bloom_probed = 0
        bloom_fp = 0
        with self._fence, self._tracer.span(
            f"{self._span_prefix}.probe", keys=int(len(fps)),
            shard=self._shard,
        ) as sp:
            for tier, runs in (("l1", self.l1), ("l2", self.l2)):
                for run in runs:
                    rem = np.flatnonzero(~found)
                    if len(rem) == 0:
                        break
                    passed0 = stats.get("bloom_passed", 0)
                    sub = run.probe(fps[rem], stats)
                    found[rem] = sub
                    hits[tier] += int(sub.sum())
                    # Bloom audit: keys this run's BLOOM LAYER passed
                    # (range filters excluded — they are exact, and
                    # counting their rejects would dilute the rate) that
                    # the run then did not contain are observed false
                    # positives. Tracked against bloom.DESIGN_FP_RATE.
                    passed = stats.get("bloom_passed", 0) - passed0
                    bloom_probed += len(rem)
                    bloom_fp += max(0, passed - int(sub.sum()))
            sp.set(
                hits_l1=hits["l1"],
                hits_l2=hits["l2"],
                blocks_decoded=stats.get("blocks_decoded", 0),
                bloom_rejects=stats.get("bloom_rejects", 0),
                bloom_fp=bloom_fp,
            )
        self._instr.probe_batches.inc()
        self._instr.probe_keys.inc(int(len(fps)))
        self._instr.probe_hits_l1.inc(hits["l1"])
        self._instr.probe_hits_l2.inc(hits["l2"])
        self._instr.blocks_decoded.inc(stats.get("blocks_decoded", 0))
        self._instr.bloom_rejects.inc(stats.get("bloom_rejects", 0))
        self._instr.bloom_probes.inc(bloom_probed)
        self._instr.bloom_fps.inc(bloom_fp)
        return found

    # -- checkpoint round trip --------------------------------------------

    def export_state(self) -> dict:
        """Self-contained checkpoint payload (L2 payloads are read back in
        — a spill file may not exist on the restoring machine). The
        per-run state dicts are immutable snapshots (runs never mutate in
        place), so a payload exported at an epoch barrier stays valid
        even if later evictions merge or spill the live tier lists —
        what lets the async engine hand the pickle to its worker."""
        with self._fence:
            return {
                "seq": self._seq,
                "l1": [r.to_state() for r in self.l1],
                "l2": [r.to_state() for r in self.l2],
            }

    def load_state(self, state: dict) -> None:
        """Restores runs from a checkpoint (CRC-validated per run); L2
        runs re-spill to this store's ``spill_dir`` when it has one, else
        they stay host-resident (still budget-enforced on the next
        eviction)."""
        with self._fence:
            self._seq = int(state.get("seq", 0))
            self.l1 = [
                FingerprintRun.from_state(s) for s in state.get("l1", [])
            ]
            l2 = [FingerprintRun.from_state(s) for s in state.get("l2", [])]
            if self._spill_dir is not None:
                l2 = [self._spill_run(r) for r in l2]
            self.l2 = l2
        self._instr.refresh()


class TenantPartitions:
    """Per-tenant host-tier partitions for the tenant-packed wave engine
    (``checker/packed_tenancy.py``).

    The packed engine's shared device table holds SALTED keys, which
    cannot be attributed to a tenant after the fact — so the host tiers
    are partitioned up front: each tenant gets its own
    ``TieredVisitedStore`` holding its ORIGINAL (unsalted) fingerprints.
    An eviction drains each tenant's since-last-eviction L0 claims (the
    engine knows them exactly — they are its parent-log stream) into that
    tenant's partition, and each wave's two-phase probe runs per tenant
    against its own partition. A tenant's partition is therefore
    membership-equivalent to the solo run's tiered store, its export
    rides the tenant's preempt payload slice unchanged, and dropping a
    tenant frees its tiers without touching anyone else's.

    Same threading contract as ``TieredVisitedStore``: under the async
    packed pipeline every probe/evict runs on the one pipeline worker in
    FIFO order (the merge fence); the per-store locks remain as the
    second fence for cross-thread snapshot readers.
    """

    def __init__(
        self,
        host_budget_mib=None,
        spill_dir=None,
        prefix: str = "pack",
        tracer=None,
    ):
        self._host_budget_mib = host_budget_mib
        self._spill_dir = spill_dir
        self._prefix = prefix
        self._tracer = tracer
        self._stores: dict = {}

    def store(self, tenant_key, registry=None) -> TieredVisitedStore:
        """The tenant's partition, created on first use. ``registry`` (the
        tenant's run-scoped metrics registry) binds the partition's
        storage instruments to that tenant's ``/metrics`` view."""
        st = self._stores.get(tenant_key)
        if st is None:
            spill = self._spill_dir
            if spill is not None:
                spill = os.path.join(spill, f"tenant-{tenant_key}")
                os.makedirs(spill, exist_ok=True)
            st = TieredVisitedStore(
                host_budget_mib=self._host_budget_mib,
                spill_dir=spill,
                instruments=StorageInstruments(
                    self._prefix, registry=registry
                ),
                tracer=self._tracer,
                # Chaos specs target one tenant's partition by this tag.
                owner=tenant_key,
            )
            self._stores[tenant_key] = st
        return st

    def get(self, tenant_key):
        """The tenant's partition, or None (never probed/evicted)."""
        return self._stores.get(tenant_key)

    def drop(self, tenant_key) -> None:
        """Forgets a departed tenant's partition (its runs free with it)."""
        self._stores.pop(tenant_key, None)

    def is_empty(self, tenant_key) -> bool:
        st = self._stores.get(tenant_key)
        return st is None or st.is_empty()

    def items(self):
        return list(self._stores.items())
