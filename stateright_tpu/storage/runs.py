"""Delta-compressed sorted fingerprint runs (the L1/L2 on-host format).

A run is an immutable sorted array of distinct u64 fingerprints stored as
varint-encoded consecutive deltas, chopped into ``RUN_BLOCK``-key blocks:

- ``block_firsts[b]`` — the first fingerprint of block ``b``, absolute
  (the binary-search directory: ``searchsorted`` picks the one candidate
  block per probe key);
- ``block_offsets[b] : block_offsets[b+1]`` — the byte range of block
  ``b``'s payload, which encodes the block's REMAINING keys as varint
  deltas from the previous key (blocks decode independently);
- a per-run Bloom filter (``bloom.BloomFilter``, <1% FP) prefilters
  probes so runs that cannot contain a key cost O(k) bit reads, and
- a CRC32 over the payload + structural invariants, checked when a
  checkpoint restores the run (round-trip validation).

The payload lives in host memory (L1) or in a file under the spill
directory (L2) — probes are uniform, only ``_payload_slice`` differs.
Sorted-delta + varint typically lands ~2-3x under raw 8 B/key on dense
fingerprint populations; ``compression_ratio`` reports the real figure.

Encode/decode are fully vectorized numpy (no per-key Python loops): the
varint byte stream is built/parsed with at most 10 masked passes (the max
byte length of a u64 varint), which batches whole blocks per pass.
"""

from __future__ import annotations

import os
import zlib
from typing import Optional

import numpy as np

__all__ = [
    "RUN_BLOCK",
    "FingerprintRun",
    "encode_varint_u64",
    "decode_varint_u64",
    "encode_sorted_fps",
    "decode_sorted_fps",
]

# Keys per block: 4096 keys ≈ a few KiB compressed — one block decode per
# probe hit candidate, small enough that a miss costs microseconds.
RUN_BLOCK = 4096


def _varint_sizes(vals: np.ndarray) -> np.ndarray:
    sizes = np.ones(len(vals), np.int64)
    for shift in range(7, 64, 7):
        sizes += vals >= (np.uint64(1) << np.uint64(shift))
    return sizes


def encode_varint_u64(vals: np.ndarray) -> bytes:
    """LEB128 encoding of a u64 array, vectorized over masked byte passes."""
    vals = np.asarray(vals, np.uint64)
    if len(vals) == 0:
        return b""
    sizes = _varint_sizes(vals)
    ends = np.cumsum(sizes)
    starts = ends - sizes
    out = np.zeros(int(ends[-1]), np.uint8)
    for i in range(int(sizes.max())):
        sel = sizes > i
        byte = (
            (vals[sel] >> np.uint64(7 * i)) & np.uint64(0x7F)
        ).astype(np.uint8)
        cont = (sizes[sel] - 1 > i).astype(np.uint8)
        out[starts[sel] + i] = byte | (cont << 7)
    return out.tobytes()


def decode_varint_u64(buf: bytes) -> np.ndarray:
    """Inverse of ``encode_varint_u64`` (terminator bytes have the MSB
    clear, so the value boundaries fall out of one flatnonzero)."""
    data = np.frombuffer(buf, np.uint8)
    if len(data) == 0:
        return np.zeros(0, np.uint64)
    ends = np.flatnonzero(data < 128)
    starts = np.empty(len(ends), np.int64)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    sizes = ends - starts + 1
    vals = np.zeros(len(starts), np.uint64)
    for i in range(int(sizes.max())):
        sel = sizes > i
        vals[sel] |= (
            data[starts[sel] + i] & np.uint8(0x7F)
        ).astype(np.uint64) << np.uint64(7 * i)
    return vals


# -- cross-host wire codec -------------------------------------------------
#
# The sharded checker's inter-host paths (multi-process eviction exchange,
# fleet spill) ship sorted fingerprint batches between processes. The wire
# frame is the same sorted-delta varint stream the runs use, framed with a
# magic + count header so a truncated or mis-routed buffer fails loudly
# instead of decoding into garbage keys.

_WIRE_MAGIC = b"FPD1"


def encode_sorted_fps(fps: np.ndarray) -> bytes:
    """Frames a SORTED (ascending, distinct) u64 fingerprint batch as
    ``b"FPD1" + <u4 count> + varint(deltas)`` where ``deltas[0]`` is the
    first key absolute and the rest are consecutive differences. An empty
    batch is the 8-byte header alone."""
    fps = np.ascontiguousarray(fps, np.uint64)
    header = _WIRE_MAGIC + np.uint32(len(fps)).tobytes()
    if len(fps) == 0:
        return header
    deltas = np.empty(len(fps), np.uint64)
    deltas[0] = fps[0]
    # uint64 subtraction wraps mod 2**64; cumsum on decode wraps back, so
    # the round trip is exact even if the input is (wrongly) unsorted.
    np.subtract(fps[1:], fps[:-1], out=deltas[1:])
    return header + encode_varint_u64(deltas)


def decode_sorted_fps(buf: bytes) -> np.ndarray:
    """Inverse of :func:`encode_sorted_fps`; validates frame + count."""
    if len(buf) < 8 or buf[:4] != _WIRE_MAGIC:
        raise ValueError("bad fingerprint wire frame (magic mismatch)")
    count = int(np.frombuffer(buf[4:8], np.uint32)[0])
    deltas = decode_varint_u64(buf[8:])
    if len(deltas) != count:
        raise ValueError(
            f"fingerprint wire frame declares {count} keys, "
            f"payload decodes {len(deltas)}"
        )
    return np.cumsum(deltas, dtype=np.uint64)


class FingerprintRun:
    """One immutable sorted run. Build with :meth:`build`; move to disk
    with :meth:`spill`; serialize with :meth:`to_state`."""

    def __init__(
        self,
        count: int,
        block_firsts: np.ndarray,
        block_offsets: np.ndarray,
        bloom,
        crc: int,
        payload: Optional[bytes] = None,
        path: Optional[str] = None,
    ):
        assert (payload is None) != (path is None)
        self.count = int(count)
        self.block_firsts = np.asarray(block_firsts, np.uint64)
        self.block_offsets = np.asarray(block_offsets, np.int64)
        self.bloom = bloom
        self.crc = int(crc)
        self.payload = payload
        self.path = path
        self.payload_nbytes = int(self.block_offsets[-1])
        self.max_fp = None  # set by build/from_state
        self._fh = None  # lazily-opened spill file (hot probe path)

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, fps: np.ndarray) -> "FingerprintRun":
        """A run from sorted, strictly-increasing, non-empty u64 keys."""
        from .bloom import BloomFilter

        fps = np.asarray(fps, np.uint64)
        n = len(fps)
        assert n > 0, "runs are never empty"
        firsts = fps[::RUN_BLOCK].copy()
        chunks = []
        offsets = np.zeros(len(firsts) + 1, np.int64)
        for b in range(len(firsts)):
            block = fps[b * RUN_BLOCK : (b + 1) * RUN_BLOCK]
            chunks.append(encode_varint_u64(np.diff(block)))
            offsets[b + 1] = offsets[b] + len(chunks[-1])
        payload = b"".join(chunks)
        run = cls(
            count=n,
            block_firsts=firsts,
            block_offsets=offsets,
            bloom=BloomFilter.build(fps),
            crc=zlib.crc32(payload),
            payload=payload,
        )
        run.max_fp = np.uint64(fps[-1])
        return run

    # -- payload access (uniform across host bytes and spill files) -------

    def _payload_slice(self, lo: int, hi: int) -> bytes:
        if self.payload is not None:
            return self.payload[lo:hi]
        # One handle per spilled run, opened lazily and kept: the probe
        # path decodes a block per candidate per wave, and an
        # open/seek/close trio per decode would dominate small reads.
        if self._fh is None:
            self._fh = open(self.path, "rb")
        self._fh.seek(lo)
        return self._fh.read(hi - lo)

    def _payload_bytes(self) -> bytes:
        if self.payload is not None:
            return self.payload
        return self._payload_slice(0, self.payload_nbytes)

    def _block_len(self, b: int) -> int:
        return min(RUN_BLOCK, self.count - b * RUN_BLOCK)

    def decode_block(self, b: int) -> np.ndarray:
        deltas = decode_varint_u64(
            self._payload_slice(
                int(self.block_offsets[b]), int(self.block_offsets[b + 1])
            )
        )
        out = np.empty(len(deltas) + 1, np.uint64)
        out[0] = self.block_firsts[b]
        out[1:] = self.block_firsts[b] + np.cumsum(deltas, dtype=np.uint64)
        return out

    def decode_all(self) -> np.ndarray:
        """The full sorted key array (merge path)."""
        if self.count == 0:
            return np.zeros(0, np.uint64)
        return np.concatenate(
            [self.decode_block(b) for b in range(len(self.block_firsts))]
        )

    # -- probe -------------------------------------------------------------

    def probe(self, fps: np.ndarray, stats: Optional[dict] = None) -> np.ndarray:
        """Membership mask for a u64 key batch: Bloom prefilter, then one
        block decode + binary search per surviving candidate's block."""
        fps = np.asarray(fps, np.uint64)
        found = np.zeros(len(fps), bool)
        if len(fps) == 0 or self.count == 0:
            return found
        bloom_pass = self.bloom.contains(fps)
        cand = bloom_pass.copy()
        if self.max_fp is not None:
            cand &= fps <= self.max_fp
        cand &= fps >= self.block_firsts[0]
        if stats is not None:
            # bloom_rejects keeps its original prefilter semantics
            # (Bloom + range); bloom_passed counts the Bloom layer ALONE
            # so the FP audit (tiered.py) measures the filter itself —
            # folding range rejects in would dilute the observed rate to
            # near zero on narrow-range runs and hide Bloom drift.
            stats["bloom_rejects"] = stats.get("bloom_rejects", 0) + int(
                len(fps) - cand.sum()
            )
            stats["bloom_passed"] = stats.get("bloom_passed", 0) + int(
                bloom_pass.sum()
            )
        if not cand.any():
            return found
        idx = np.flatnonzero(cand)
        qs = fps[idx]
        blk = np.searchsorted(self.block_firsts, qs, side="right") - 1
        hits = np.zeros(len(qs), bool)
        for b in np.unique(blk):
            sel = blk == b
            arr = self.decode_block(int(b))
            pos = np.searchsorted(arr, qs[sel])
            pos = np.minimum(pos, len(arr) - 1)
            hits[sel] = arr[pos] == qs[sel]
            if stats is not None:
                stats["blocks_decoded"] = stats.get("blocks_decoded", 0) + 1
        found[idx] = hits
        return found

    # -- spill / serialization --------------------------------------------

    def close(self) -> None:
        """Closes the spill-file handle (L2 compaction retires runs; a
        long run must not accumulate one fd per retired file)."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def spill(self, path: str) -> "FingerprintRun":
        """Writes the payload to ``path`` (atomic tmp+rename) and returns
        the disk-backed twin; index + bloom stay in host memory."""
        data = self._payload_bytes()
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        run = FingerprintRun(
            count=self.count,
            block_firsts=self.block_firsts,
            block_offsets=self.block_offsets,
            bloom=self.bloom,
            crc=self.crc,
            path=path,
        )
        run.max_fp = self.max_fp
        return run

    @property
    def host_nbytes(self) -> int:
        """Host-memory footprint: payload (when resident) + index + bloom."""
        index = self.block_firsts.nbytes + self.block_offsets.nbytes
        payload = len(self.payload) if self.payload is not None else 0
        return payload + index + self.bloom.nbytes

    @property
    def disk_nbytes(self) -> int:
        return self.payload_nbytes if self.path is not None else 0

    def to_state(self) -> dict:
        """Checkpoint form: payload embedded (checkpoints must be
        self-contained — a spill file may not survive the machine the
        checkpoint migrates to)."""
        return {
            "count": self.count,
            "block_firsts": self.block_firsts,
            "block_offsets": self.block_offsets,
            "payload": self._payload_bytes(),
            "bloom": self.bloom.to_state(),
            "crc": self.crc,
            "max_fp": None if self.max_fp is None else int(self.max_fp),
        }

    @classmethod
    def from_state(cls, state: dict) -> "FingerprintRun":
        """Round-trip validation: the payload CRC and the block structure
        must match what the writer recorded, or the restore is refused —
        a torn checkpoint must never silently drop visited states (which
        would re-expand them and corrupt counts)."""
        from .bloom import BloomFilter

        payload = state["payload"]
        if zlib.crc32(payload) != state["crc"]:
            raise ValueError(
                "fingerprint-run payload CRC mismatch: the checkpoint's "
                "storage tier is corrupt; refusing to resume from it"
            )
        firsts = np.asarray(state["block_firsts"], np.uint64)
        offsets = np.asarray(state["block_offsets"], np.int64)
        count = int(state["count"])
        if (
            len(offsets) != len(firsts) + 1
            or int(offsets[-1]) != len(payload)
            or len(firsts) != -(-count // RUN_BLOCK)
        ):
            raise ValueError(
                "fingerprint-run block structure does not match its "
                "payload; refusing to resume from a corrupt checkpoint"
            )
        run = cls(
            count=count,
            block_firsts=firsts,
            block_offsets=offsets,
            bloom=BloomFilter.from_state(state["bloom"]),
            crc=int(state["crc"]),
            payload=payload,
        )
        run.max_fp = (
            None if state.get("max_fp") is None else np.uint64(state["max_fp"])
        )
        # The CRC pins the payload but not the header fields; decode the
        # last block (cheap) and check it against the recorded count and
        # max key so a tampered/torn header cannot shift probe results.
        last = run.decode_block(len(firsts) - 1)
        want_len = run._block_len(len(firsts) - 1)
        if len(last) != want_len or (
            run.max_fp is not None and last[-1] != run.max_fp
        ):
            raise ValueError(
                "fingerprint-run header does not match its payload; "
                "refusing to resume from a corrupt checkpoint"
            )
        return run
