"""Host tier for the device condition-false edge store.

``ops/edge_store.py`` keeps the edge relation device-resident and
capacity-budgeted; when a wave could overflow it, the checker drains the
filled rows here — the same L0→host eviction discipline as the tiered
visited store, specialized for the liveness edge relation. The store
also owns the two small side tables the end-of-run analysis needs:

- **roots**: per eventually-property fingerprints of condition-false
  *init* states (the only legal starting points of a counterexample
  path);
- **terminals**: per-property fingerprints of condition-false states
  with no within-boundary successors at all (the masked-terminal
  certificate's anchor).

Edge chunks are stored per eviction as sorted-deduped structured numpy
arrays (parent64, child64, emask) — duplicate edges from table-growth
retries collapse at absorb time, so memory tracks the DISTINCT relation,
not the dispatch count. ``host_budget_mib`` spills absorbed chunks to
``spill_dir`` as ``.npz`` files (CRC-validated on read-back), mirroring
the L1→L2 discipline of ``storage/tiered.py``.

The whole store rides the checkpoint payload (the v3 extension — see
``checker/tpu.py``'s header note): a preempted or periodically
checkpointed run restores it bit-identically, so the final verdict never
depends on where the run was cut.
"""

from __future__ import annotations

import os
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.faults import fault_point

__all__ = ["LivenessEdgeStore", "LivenessInstruments"]


class LivenessInstruments:
    """Counters/gauges for one checker's liveness edge store, named
    ``<prefix>.liveness.*`` (the PR 8 ledger family
    ``coverage_report.py`` renders alongside the met-bit population)."""

    def __init__(self, prefix: str, registry=None):
        if registry is None:
            from ..telemetry import metrics_registry

            registry = metrics_registry()
        p = f"{prefix}.liveness"
        self.prefix = p
        self.edges = registry.counter(f"{p}.edge_store.edges_logged")
        self.evictions = registry.counter(f"{p}.edge_store.evictions")
        self.spills = registry.counter(f"{p}.edge_store.spills")
        self.host_bytes = registry.gauge(f"{p}.edge_store.host_bytes")
        self.occupancy = registry.gauge(f"{p}.edge_store.occupancy")
        self.analysis_seconds = registry.gauge(f"{p}.analysis_seconds")
        self.trim_rounds = registry.counter(f"{p}.trim_rounds")
        self.counterexamples = registry.counter(f"{p}.counterexamples")
        self.absences = registry.counter(f"{p}.absences_certified")

    def record_evict(self, n_edges: int, host_bytes: int) -> None:
        self.edges.inc(n_edges)
        self.evictions.inc()
        self.host_bytes.set(host_bytes)

    def record_spill(self, nbytes: int) -> None:
        self.spills.inc()


def _pack_cols(parent64, child64, emask) -> np.ndarray:
    """One absorbed chunk as a (n, 3) uint64 array (emask widened) —
    a single contiguous allocation that np.unique can sort by rows."""
    out = np.empty((len(parent64), 3), np.uint64)
    out[:, 0] = parent64
    out[:, 1] = child64
    out[:, 2] = emask.astype(np.uint64)
    return out


class LivenessEdgeStore:
    """Host-resident condition-false edge relation for one checker (or
    one packed tenant). Thread discipline matches the tiered store:
    absorbs may run on the async pipeline worker (FIFO-serialized), the
    analysis reads only after the run-end barrier."""

    def __init__(self, instruments=None, spill_dir: Optional[str] = None,
                 host_budget_mib: Optional[float] = None, owner=None):
        self._chunks: List[np.ndarray] = []
        # Spilled chunk file paths, in absorb order.
        self._spilled: List[str] = []
        self._spill_dir = spill_dir
        self._budget_bytes = (
            int(host_budget_mib * (1 << 20))
            if host_budget_mib is not None
            else None
        )
        self._host_bytes = 0
        self._owner = owner
        self._seq = 0
        self._lock = threading.Lock()
        # fp64 -> per-property bit mask (u32 bits = eventually slots).
        self.roots: Dict[int, int] = {}
        self.terminals: Dict[int, int] = {}
        self.edges_logged = 0       # rows absorbed (pre-dedup)
        self.evictions = 0
        self._ins = instruments

    # -- absorb (the eviction target) ---------------------------------------

    def absorb(self, phi, plo, chi, clo, emask, tmask) -> None:
        """One device-store eviction: raw u32 columns of the filled
        prefix. Edge rows (emask != 0) dedup into a sorted chunk;
        terminal rows (tmask != 0) land in the per-property terminal
        sets. Runs on the checker thread or the async pipeline worker —
        FIFO keeps absorb order deterministic either way."""
        # Injection seam: the absorb is host work over device pulls —
        # a numpy OOM or spill ENOSPC here must fault the run visibly,
        # never silently drop edges (a dropped edge is an unsound
        # "absence" verdict later).
        fault_point("liveness.edge_evict", tenant=self._owner)
        phi = np.asarray(phi)
        plo = np.asarray(plo)
        emask = np.asarray(emask)
        tmask = np.asarray(tmask)
        p64 = (phi.astype(np.uint64) << np.uint64(32)) | plo.astype(
            np.uint64
        )
        esel = emask != 0
        n_edges = int(esel.sum())
        with self._lock:
            self.edges_logged += n_edges
            self.evictions += 1
        if n_edges:
            chi = np.asarray(chi)
            clo = np.asarray(clo)
            c64 = (chi.astype(np.uint64) << np.uint64(32)) | clo.astype(
                np.uint64
            )
            chunk = np.unique(
                _pack_cols(p64[esel], c64[esel], emask[esel]), axis=0
            )
            with self._lock:
                self._chunks.append(chunk)
                self._host_bytes += chunk.nbytes
            self._enforce_budget()
        tsel = tmask != 0
        if tsel.any():
            for fp, m in zip(p64[tsel], tmask[tsel]):
                self.add_terminal(int(fp), int(m))
        if self._ins is not None:
            self._ins.record_evict(n_edges, self._host_bytes)

    def add_roots(self, fp64s, masks) -> None:
        """Condition-false init fingerprints with their per-property
        bit masks (recorded once at seed time, restored on resume)."""
        with self._lock:
            for fp, m in zip(np.asarray(fp64s), np.asarray(masks)):
                if int(m):
                    self.roots[int(fp)] = self.roots.get(int(fp), 0) | int(m)

    def add_terminal(self, fp64: int, mask: int) -> None:
        with self._lock:
            self.terminals[fp64] = self.terminals.get(fp64, 0) | mask

    # -- budget / spill ------------------------------------------------------

    def _enforce_budget(self) -> None:
        if self._budget_bytes is None or self._spill_dir is None:
            return
        with self._lock:
            while self._host_bytes > self._budget_bytes and self._chunks:
                chunk = self._chunks.pop(0)
                self._seq += 1
                path = os.path.join(
                    self._spill_dir,
                    f"liveness-edges-{id(self):x}-{self._seq}.npz",
                )
                # Spill BEFORE dropping the in-memory copy (a failed
                # write must not lose the chunk from both tiers — the
                # PR 13 _enforce_host_budget lesson).
                fault_point("storage.spill", tenant=self._owner)
                np.savez(path, edges=chunk,
                         crc=np.uint64(zlib.crc32(chunk.tobytes())))
                self._spilled.append(path)
                self._host_bytes -= chunk.nbytes
                if self._ins is not None:
                    self._ins.record_spill(chunk.nbytes)

    def _load_spilled(self) -> List[np.ndarray]:
        out = []
        for path in self._spilled:
            with np.load(path) as z:
                chunk = z["edges"]
                if zlib.crc32(chunk.tobytes()) != int(z["crc"]):
                    raise ValueError(
                        f"liveness edge spill {path} failed CRC validation"
                    )
                out.append(chunk)
        return out

    # -- analysis-side reads -------------------------------------------------

    def edge_rows(self) -> np.ndarray:
        """The full deduped relation as one (n, 3) uint64 array
        (parent64, child64, emask) — spilled chunks re-read and
        CRC-checked. Analysis-time only."""
        with self._lock:
            chunks = list(self._chunks)
        chunks = self._load_spilled() + chunks
        if not chunks:
            return np.empty((0, 3), np.uint64)
        allr = np.concatenate(chunks)
        # Merge emasks of duplicate (parent, child) pairs across chunks
        # (a pair can log under different property bits in different
        # waves if conditions flip — masks OR together).
        order = np.lexsort((allr[:, 1], allr[:, 0]))
        allr = allr[order]
        same = np.concatenate(
            [[False], (allr[1:, 0] == allr[:-1, 0])
             & (allr[1:, 1] == allr[:-1, 1])]
        )
        group = np.cumsum(~same) - 1
        n_groups = int(group[-1]) + 1 if len(group) else 0
        emask = np.zeros((n_groups,), np.uint64)
        np.bitwise_or.at(emask, group, allr[:, 2])
        firsts = np.flatnonzero(~same)
        out = allr[firsts]
        out[:, 2] = emask
        return out

    def property_slice(self, bit: int, rows: Optional[np.ndarray] = None,
                       ) -> Tuple[np.ndarray, np.ndarray,
                                  np.ndarray, np.ndarray]:
        """(src64, dst64, roots64, terminals64) for one eventually
        property's bit in the masks. ``rows`` (an ``edge_rows()``
        result) lets multi-property analyses pay the spill re-read and
        full-relation dedup once instead of once per property."""
        if rows is None:
            rows = self.edge_rows()
        b = np.uint64(1 << bit)
        sel = (rows[:, 2] & b) != 0
        with self._lock:
            roots = np.array(
                [fp for fp, m in self.roots.items() if m & (1 << bit)],
                np.uint64,
            )
            terms = np.array(
                [fp for fp, m in self.terminals.items() if m & (1 << bit)],
                np.uint64,
            )
        return rows[sel, 0], rows[sel, 1], roots, terms

    def stats(self) -> dict:
        with self._lock:
            return {
                "edges_logged": self.edges_logged,
                "evictions": self.evictions,
                "chunks": len(self._chunks),
                "spilled_chunks": len(self._spilled),
                "host_bytes": self._host_bytes,
                "roots": len(self.roots),
                "terminals": len(self.terminals),
            }

    # -- checkpoint (the v3 payload extension) -------------------------------

    def export_state(self) -> dict:
        """The store as a checkpoint payload fragment (spilled chunks
        folded back in — the checkpoint must be self-contained; CRC
        guards the restore)."""
        rows = self.edge_rows()
        with self._lock:
            return {
                "edges": rows,
                "crc": zlib.crc32(rows.tobytes()),
                "roots": dict(self.roots),
                "terminals": dict(self.terminals),
                "edges_logged": self.edges_logged,
                "evictions": self.evictions,
            }

    def load_state(self, state: dict) -> None:
        rows = np.asarray(state["edges"], np.uint64).reshape(-1, 3)
        if zlib.crc32(rows.tobytes()) != state["crc"]:
            raise ValueError(
                "liveness edge-store checkpoint failed CRC validation"
            )
        with self._lock:
            if len(rows):
                self._chunks.append(rows)
                self._host_bytes += rows.nbytes
            self.roots.update(
                {int(k): int(v) for k, v in state["roots"].items()}
            )
            for fp, m in state["terminals"].items():
                cur = self.terminals.get(int(fp), 0)
                self.terminals[int(fp)] = cur | int(m)
            self.edges_logged += int(state["edges_logged"])
            self.evictions += int(state["evictions"])
