"""Named conformance-corpus persistence (``service_dir/corpus``).

One corpus = one JSONL file of wire frames (``conformance/wire.py``)
under a validated NAME — never a client-chosen path. The HTTP layer
accepts ``{"corpus": "<name>"}`` precisely because names resolve inside
this store's root; accepting paths would hand remote clients arbitrary
server-side reads (the same reasoning that keeps ``resume_from`` off
the HTTP spawn surface — see service/http.py).

Writes are atomic (tmp + rename in-directory): a killed writer leaves a
stray ``.tmp``, never a half-length corpus that would decode as a torn
frame on the next audit.
"""

from __future__ import annotations

import os
import re
import tempfile
from typing import List, Sequence

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,127}$")
_SUFFIX = ".jsonl"


def validate_corpus_name(name: str) -> str:
    """A corpus name, or ValueError: one path segment, no separators,
    no leading dot (a name is an identifier, not a location)."""
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise ValueError(
            f"invalid corpus name {name!r}: one path segment of "
            "[A-Za-z0-9._-], not starting with '.', max 128 chars"
        )
    return name


class CorpusStore:
    """Named JSONL corpora under one root directory."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def path(self, name: str) -> str:
        return os.path.join(self.root, validate_corpus_name(name) + _SUFFIX)

    def save(self, name: str, lines: Sequence[str]) -> str:
        """Atomically writes one corpus (wire lines, one frame per
        line); returns its path."""
        path = self.path(name)
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                for line in lines:
                    f.write(line.rstrip("\n") + "\n")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        return path

    def load(self, name: str) -> List[str]:
        """The corpus's wire lines; FileNotFoundError when absent (the
        HTTP layer maps it to a 400 naming the store's contents)."""
        with open(self.path(name), encoding="utf-8") as f:
            return [ln.rstrip("\n") for ln in f if ln.strip()]

    def list(self) -> List[str]:
        out = []
        try:
            entries = os.listdir(self.root)
        except OSError:
            return out
        for fn in entries:
            if fn.endswith(_SUFFIX):
                out.append(fn[: -len(_SUFFIX)])
        return sorted(out)
