"""Static Bloom filter over u64 fingerprints (numpy-only, batched probes).

Runs are immutable once built (`runs.FingerprintRun`), so the filter is
static too: built once from the sorted fingerprint array, never mutated.
Sizing targets <1% false positives: ~10 bits/key with k=7 hash functions
gives a theoretical FP rate of ~0.8% at the design load (the classic
``(1 - e^{-kn/m})^k`` optimum is k = m/n·ln2 ≈ 6.9). Probes and
construction are fully vectorized — the host-exit probe path handles
whole wave batches, never per-key Python loops.

Index derivation is double hashing over two independent splitmix64-style
finalizer mixes: ``idx_i = (h1 + i·h2) mod m`` with m a power of two, the
standard Kirsch–Mitzenmacher construction (asymptotically as good as k
independent hashes).
"""

from __future__ import annotations

import numpy as np

__all__ = ["BloomFilter", "DESIGN_FP_RATE"]

# ~10 bits/key at k=7: <1% false-positive rate at design load.
BITS_PER_KEY = 10
NUM_HASHES = 7
# THE configured false-positive bound the sizing above targets (theory:
# ~0.8% at design load). The observed rate is audited against this bound
# by the `*.storage.host_probe.bloom_*` counters (tiered.py) — a two-phase
# probe whose Bloom layer drifts past it is silently wasting block
# decodes, which only an observed-vs-configured comparison can catch.
DESIGN_FP_RATE = 0.01

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_M3 = np.uint64(0xFF51AFD7ED558CCD)
_M4 = np.uint64(0xC4CEB9FE1A85EC53)


def _mix(x: np.ndarray, m_a: np.uint64, m_b: np.uint64) -> np.ndarray:
    """splitmix64/murmur3-style avalanche (uint64 wraparound is the point)."""
    x = x.astype(np.uint64, copy=True)
    x ^= x >> np.uint64(30)
    x *= m_a
    x ^= x >> np.uint64(27)
    x *= m_b
    x ^= x >> np.uint64(31)
    return x


class BloomFilter:
    """Immutable filter; ``words`` is the uint64 bit array, ``m_bits`` its
    power-of-two bit count."""

    def __init__(self, words: np.ndarray, n_keys: int):
        self.words = np.ascontiguousarray(words, dtype=np.uint64)
        self.n_keys = int(n_keys)
        self.m_bits = len(self.words) * 64

    @classmethod
    def build(cls, fps: np.ndarray) -> "BloomFilter":
        fps = np.asarray(fps, dtype=np.uint64)
        n = len(fps)
        # Power-of-two bit count >= BITS_PER_KEY per key (min one word).
        want = max(64, n * BITS_PER_KEY)
        m = 1 << (want - 1).bit_length()
        words = np.zeros(m // 64, dtype=np.uint64)
        if n:
            for idx in cls._indices(fps, m):
                np.bitwise_or.at(
                    words, idx >> np.uint64(6),
                    np.uint64(1) << (idx & np.uint64(63)),
                )
        return cls(words, n)

    @staticmethod
    def _indices(fps: np.ndarray, m_bits: int):
        mask = np.uint64(m_bits - 1)
        h1 = _mix(fps, _M1, _M2)
        # Odd step so every (h1, h2) pair walks the whole table.
        h2 = _mix(fps, _M3, _M4) | np.uint64(1)
        for i in range(NUM_HASHES):
            yield (h1 + np.uint64(i) * h2) & mask

    def contains(self, fps: np.ndarray) -> np.ndarray:
        """Membership mask (with false positives, never false negatives)."""
        fps = np.asarray(fps, dtype=np.uint64)
        out = np.ones(len(fps), dtype=bool)
        if self.n_keys == 0:
            out[:] = False
            return out
        for idx in self._indices(fps, self.m_bits):
            out &= (
                self.words[idx >> np.uint64(6)]
                >> (idx & np.uint64(63))
            ) & np.uint64(1) != 0
            if not out.any():
                break
        return out

    @property
    def nbytes(self) -> int:
        return self.words.nbytes

    # -- checkpoint round trip --------------------------------------------

    def to_state(self) -> dict:
        return {"words": self.words, "n_keys": self.n_keys}

    @classmethod
    def from_state(cls, state: dict) -> "BloomFilter":
        return cls(np.asarray(state["words"], np.uint64), state["n_keys"])
