"""Stable 64-bit state fingerprinting.

The reference derives a stable 64-bit digest per state via a fixed-seed hasher
(``/root/reference/src/lib.rs:329-375``) so fingerprints are reproducible across
runs — a requirement for path-by-fingerprint reconstruction and golden tests.

This implementation hashes a canonical byte encoding of the state with
blake2b(digest_size=8). Unordered containers (set/frozenset/dict) are hashed
order-insensitively by hashing each entry to a u64, sorting the u64s, and
feeding them to the outer hasher — mirroring the reference's
``HashableHashSet``/``HashableHashMap`` strategy (``/root/reference/src/util.rs:137-159``).

The same canonical u64 is computed on-device for packed states by
``stateright_tpu.ops.fingerprint`` (a different hash function — device
fingerprints only need to be stable *within* the device backend).
"""

from __future__ import annotations

import dataclasses
from hashlib import blake2b
from typing import Any

__all__ = ["fingerprint", "stable_encode", "stable_hash", "Fingerprint"]

# A fingerprint is a nonzero unsigned 64-bit int (reference: NonZeroU64).
Fingerprint = int

_MASK64 = (1 << 64) - 1

# Type tags keep the encoding prefix-free across types so e.g. (1, 2) and
# ((1,), 2) cannot collide byte-wise.
_T_NONE = b"\x00"
_T_BOOL = b"\x01"
_T_INT = b"\x02"
_T_BIGINT = b"\x03"
_T_STR = b"\x04"
_T_BYTES = b"\x05"
_T_SEQ = b"\x06"
_T_SET = b"\x07"
_T_MAP = b"\x08"
_T_OBJ = b"\x09"
_T_FLOAT = b"\x0a"


def _encode(value: Any, out: bytearray) -> None:
    """Append the canonical encoding of ``value`` to ``out``."""
    if value is None:
        out += _T_NONE
    elif value is True:
        out += _T_BOOL
        out += b"\x01"
    elif value is False:
        out += _T_BOOL
        out += b"\x00"
    elif type(value) is int:
        if -(1 << 63) <= value < (1 << 63):
            out += _T_INT
            out += value.to_bytes(8, "little", signed=True)
        else:
            b = value.to_bytes((value.bit_length() + 8) // 8, "little", signed=True)
            out += _T_BIGINT
            out += len(b).to_bytes(4, "little")
            out += b
    elif type(value) is str:
        b = value.encode()
        out += _T_STR
        out += len(b).to_bytes(4, "little")
        out += b
    elif type(value) is bytes:
        out += _T_BYTES
        out += len(value).to_bytes(4, "little")
        out += value
    elif type(value) is float:
        out += _T_FLOAT
        out += value.hex().encode()
    elif type(value) is tuple or type(value) is list:
        out += _T_SEQ
        out += len(value).to_bytes(4, "little")
        for item in value:
            _encode(item, out)
    elif type(value) is frozenset or type(value) is set:
        # Order-insensitive: sorted per-element digests.
        out += _T_SET
        out += len(value).to_bytes(4, "little")
        for h in sorted(stable_hash(item) for item in value):
            out += h.to_bytes(8, "little")
    elif type(value) is dict:
        out += _T_MAP
        out += len(value).to_bytes(4, "little")
        for h in sorted(stable_hash((k, v)) for k, v in value.items()):
            out += h.to_bytes(8, "little")
    elif dataclasses.is_dataclass(value) and not isinstance(value, type):
        out += _T_OBJ
        name = type(value).__qualname__.encode()
        out += len(name).to_bytes(2, "little")
        out += name
        for f in dataclasses.fields(value):
            _encode(getattr(value, f.name), out)
    elif isinstance(value, int):
        # IntEnum and other int subclasses (incl. Id) hash as plain ints so that
        # e.g. an Id inside a message matches an Id constructed elsewhere.
        _encode(int(value), out)
    elif isinstance(value, str):
        _encode(str(value), out)
    elif hasattr(value, "__stable_fields__"):
        out += _T_OBJ
        name = type(value).__qualname__.encode()
        out += len(name).to_bytes(2, "little")
        out += name
        for field_value in value.__stable_fields__():
            _encode(field_value, out)
    elif isinstance(value, (tuple, list)):
        _encode(tuple(value), out)
    else:
        raise TypeError(
            f"Cannot stably hash value of type {type(value).__name__}: {value!r}. "
            "Use ints/strs/bytes/tuples/lists/sets/dicts/dataclasses, or define "
            "__stable_fields__() returning the hashable field values."
        )


def stable_encode(value: Any) -> bytes:
    """The canonical byte encoding of ``value``. Byte-wise comparison of
    encodings is a deterministic total order on stable-hashable values
    (used by symmetry reduction's representative sort)."""
    buf = bytearray()
    _encode(value, buf)
    return bytes(buf)


def stable_hash(value: Any) -> int:
    """Canonical stable 64-bit hash of ``value`` (may be zero)."""
    buf = bytearray()
    _encode(value, buf)
    return int.from_bytes(blake2b(bytes(buf), digest_size=8).digest(), "little")


def fingerprint(value: Any) -> Fingerprint:
    """Stable nonzero 64-bit fingerprint of a state.

    Reference: ``fingerprint()`` at ``/root/reference/src/lib.rs:332-337``.
    """
    h = stable_hash(value) & _MASK64
    return h if h != 0 else 1
