"""Counterexample/example paths, reconstructed from fingerprint sequences.

Reference: ``/root/reference/src/checker/path.rs``. Reconstruction re-executes
the model along the fingerprint trail (the TLC technique from "Model Checking
TLA+ Specifications", Yu/Manolios/Lamport). The detailed nondeterminism
diagnostics are kept — they encode real user pain.
"""

from __future__ import annotations

from typing import Generic, List, Optional, Sequence, Tuple, TypeVar

from .fingerprint import Fingerprint, fingerprint

State = TypeVar("State")
Action = TypeVar("Action")

_NONDETERMINISM_HINT = """
The most obvious cause would be a model that operates directly upon untracked external state such
as the file system, a global mutable, or a source of randomness. Note that this is often
inadvertent. For example, iterating over an unordered container does not always happen in the same
order, which can lead to unexpected nondeterminism."""


class Path(Generic[State, Action]):
    """A path of states including actions:
    ``state --action--> state ... --action--> state``."""

    def __init__(self, steps: List[Tuple[State, Optional[Action]]]):
        self._steps = steps

    @staticmethod
    def from_fingerprints(
        model, fingerprints: Sequence[Fingerprint], fp_of=None
    ) -> "Path":
        """Reconstructs a path by replaying the model along a fingerprint trail.

        ``fp_of`` overrides the fingerprint function (default: the stable host
        ``fingerprint``). The TPU checkers pass their device fingerprint of the
        packed state so host replay matches device-recorded trails.
        """
        if fp_of is None:
            fp_of = fingerprint
        fps = list(fingerprints)
        if not fps:
            raise ValueError("empty path is invalid")
        init_print = fps[0]
        last_state = None
        for s in model.init_states():
            if fp_of(s) == init_print:
                last_state = s
                break
        if last_state is None:
            available = [fp_of(s) for s in model.init_states()]
            raise RuntimeError(
                f"""
Unable to reconstruct a `Path` based on digests ("fingerprints") from states visited earlier. No
init state has the expected fingerprint ({init_print}). This usually happens when the return value
of `Model.init_states` varies.
{_NONDETERMINISM_HINT}

Available init fingerprints (none of which match): {available}"""
            )
        output: List[Tuple[State, Optional[Action]]] = []
        for next_fp in fps[1:]:
            found = None
            for a, s in model.next_steps(last_state):
                if fp_of(s) == next_fp:
                    found = (a, s)
                    break
            if found is None:
                available = [fp_of(s) for s in model.next_states(last_state)]
                raise RuntimeError(
                    f"""
Unable to reconstruct a `Path` based on digests ("fingerprints") from states visited earlier.
{1 + len(output)} previous state(s) of the path were able to be reconstructed, but no subsequent
state has the next fingerprint ({next_fp}). This usually happens when `Model.actions` or
`Model.next_state` vary even when given the same input arguments.
{_NONDETERMINISM_HINT}

Available next fingerprints (none of which match): {available}"""
                )
            action, next_state = found
            output.append((last_state, action))
            last_state = next_state
        output.append((last_state, None))
        return Path(output)

    @staticmethod
    def from_actions(model, init_state: State, actions) -> Optional["Path"]:
        """Constructs a path from an initial state and a sequence of actions.
        Returns None for inputs unreachable via the model."""
        if init_state not in model.init_states():
            return None
        output: List[Tuple[State, Optional[Action]]] = []
        prev_state = init_state
        for action in actions:
            found = None
            for a, s in model.next_steps(prev_state):
                if a == action:
                    found = (a, s)
                    break
            if found is None:
                return None
            output.append((prev_state, found[0]))
            prev_state = found[1]
        output.append((prev_state, None))
        return Path(output)

    @staticmethod
    def final_state(model, fingerprints: Sequence[Fingerprint]) -> Optional[State]:
        """The final state associated with a particular fingerprint path."""
        fps = list(fingerprints)
        if not fps:
            return None
        matching_state = None
        for s in model.init_states():
            if fingerprint(s) == fps[0]:
                matching_state = s
                break
        if matching_state is None:
            return None
        for next_print in fps[1:]:
            found = None
            for s in model.next_states(matching_state):
                if fingerprint(s) == next_print:
                    found = s
                    break
            if found is None:
                return None
            matching_state = found
        return matching_state

    def last_state(self) -> State:
        return self._steps[-1][0]

    def into_states(self) -> List[State]:
        return [s for s, _a in self._steps]

    def into_actions(self) -> List[Action]:
        return [a for _s, a in self._steps if a is not None]

    def into_vec(self) -> List[Tuple[State, Optional[Action]]]:
        return list(self._steps)

    def encode(self) -> str:
        """Encodes the path as '/'-delimited fingerprints."""
        return "/".join(str(fingerprint(s)) for s, _a in self._steps)

    def __len__(self) -> int:
        return len(self._steps)

    def __iter__(self):
        return iter(self._steps)

    def __eq__(self, other) -> bool:
        return isinstance(other, Path) and self._steps == other._steps

    def __hash__(self) -> int:
        def _key(x):
            try:
                return hash(x)
            except TypeError:
                return fingerprint(x)

        return hash(tuple((_key(s), _key(a)) for s, a in self._steps))

    def __str__(self) -> str:
        lines = [f"Path[{len(self._steps) - 1}]:"]
        for _state, action in self._steps:
            if action is not None:
                lines.append(f"- {action!r}")
        return "\n".join(lines) + "\n"

    def __repr__(self) -> str:
        return f"Path({self._steps!r})"
