"""The packed-state protocol: models whose transitions stage onto the TPU.

The reference's ``Model`` trait enumerates actions into a growable ``Vec``
(``/root/reference/src/lib.rs:172-184``) — data-dependent arity that cannot
be traced. A ``BatchableModel`` additionally exposes its transition relation
in fixed-width form (SURVEY §7 stage 5a):

- states are pytrees of fixed-shape arrays (the "packed" representation);
- the action set is a *static* dense range ``0..packed_action_count``; each
  action id either applies (guard true) or reports invalid — the analog of
  the reference enumerating only enabled actions;
- ``packed_step`` is jax-traceable over one (state, action_id) and is
  vmapped by the checkers over frontier × action grids;
- properties are traceable predicates aligned 1:1 with ``properties()``.

Packed and host representations must agree: ``pack_state``/``unpack_state``
convert between them, and two host states are equal iff their packed forms
are identical (this is what makes device fingerprints usable for dedup and
path replay).
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax

PackedState = Any  # pytree of arrays


class BatchableModel:
    """Mixin protocol implemented by models that support the TPU backends.

    A class typically subclasses both ``Model`` (host path: exact oracles,
    Explorer, paths) and ``BatchableModel`` (device path: TpuBfs, TPU
    simulation). The device checkers verify counts against the host path in
    the parity test suite.
    """

    # -- static shape info -------------------------------------------------

    def packed_action_count(self) -> int:
        """Static upper bound on actions per state (dense action ids)."""
        raise NotImplementedError

    # -- traceable transition relation ------------------------------------

    def packed_init_states(self) -> PackedState:
        """All initial states, stacked along a leading batch axis."""
        raise NotImplementedError

    def packed_step(
        self, state: PackedState, action_id: jax.Array
    ) -> Tuple[PackedState, jax.Array]:
        """One unbatched transition: ``(state, action_id) -> (next, valid)``.

        ``valid`` is a scalar bool: False when the action's guard does not
        hold in ``state`` (the action would not have been enumerated by the
        host model) or when the transition is a pruned no-op (the host
        ``next_state`` returned None). Checkers vmap this over
        frontier × action grids, so it must be jax-traceable with no
        data-dependent python control flow.
        """
        raise NotImplementedError

    def packed_conditions(self) -> List[Callable[[PackedState], jax.Array]]:
        """Traceable predicates aligned with ``properties()`` (same order).

        Each maps one unbatched packed state to a scalar bool.
        """
        raise NotImplementedError

    def packed_within_boundary(self, state: PackedState) -> jax.Array:
        """Traceable analog of ``within_boundary`` (scalar bool)."""
        import jax.numpy as jnp

        return jnp.bool_(True)

    def packed_fingerprint_view(self, state: PackedState) -> PackedState:
        """The sub-pytree of ``state`` that participates in fingerprints.

        Defaults to the whole state. Models with hash-excluded components
        override this — e.g. actor systems exclude crash flags, mirroring
        the host/reference state hash
        (``/root/reference/src/actor/model_state.rs:86-97``).
        """
        return state

    # -- host interop ------------------------------------------------------

    def pack_state(self, host_state: Any) -> PackedState:
        """Packs one host state into (numpy/jax) arrays."""
        raise NotImplementedError

    def unpack_state(self, packed: PackedState) -> Any:
        """Unpacks one packed state (concrete arrays) into a host state."""
        raise NotImplementedError
