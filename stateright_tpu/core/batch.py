"""The packed-state protocol: models whose transitions stage onto the TPU.

The reference's ``Model`` trait enumerates actions into a growable ``Vec``
(``/root/reference/src/lib.rs:172-184``) — data-dependent arity that cannot
be traced. A ``BatchableModel`` additionally exposes its transition relation
in fixed-width form (SURVEY §7 stage 5a):

- states are pytrees of fixed-shape arrays (the "packed" representation);
- the action set is a *static* dense range ``0..packed_action_count``; each
  action id either applies (guard true) or reports invalid — the analog of
  the reference enumerating only enabled actions;
- ``packed_step`` is jax-traceable over one (state, action_id) and is
  vmapped by the checkers over frontier × action grids;
- properties are traceable predicates aligned 1:1 with ``properties()``.

Packed and host representations must agree: ``pack_state``/``unpack_state``
convert between them, and two host states are equal iff their packed forms
are identical (this is what makes device fingerprints usable for dedup and
path replay).
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import numpy as np

PackedState = Any  # pytree of arrays

# Bound on the n! permutation table. Since r3 the table is only the
# verify-or-fallback path behind the WL canonical keys (see
# checker/tpu._make_key_fn) — the common case never executes it — but it
# is still materialized as a compile-time constant: 9! x 9 rows x 2
# tables x 4B = 26MB, acceptable; 10! would be 290MB, not.
MAX_SYMMETRY_ACTORS = 9


def permutation_tables(n: int) -> Tuple[np.ndarray, np.ndarray]:
    """All ``n!`` permutations as two aligned ``(n!, n)`` int32 tables:
    ``new_to_old`` rows index-gather permuted vectors
    (``permuted[new] = orig[new_to_old[new]]``) and ``old_to_new`` rows are
    the inverses, used to rewrite embedded actor ids. Device symmetry takes
    the minimum fingerprint over every row — a true orbit invariant."""
    from itertools import permutations

    if n > MAX_SYMMETRY_ACTORS:
        raise ValueError(
            f"symmetry over {n} actors needs a {n}!-row permutation table; "
            f"the supported bound is {MAX_SYMMETRY_ACTORS}"
        )
    new_to_old = np.array(list(permutations(range(n))), np.int32)
    old_to_new = np.argsort(new_to_old, axis=1).astype(np.int32)
    return new_to_old, old_to_new


class BatchableModel:
    """Mixin protocol implemented by models that support the TPU backends.

    A class typically subclasses both ``Model`` (host path: exact oracles,
    Explorer, paths) and ``BatchableModel`` (device path: TpuBfs, TPU
    simulation). The device checkers verify counts against the host path in
    the parity test suite.
    """

    # -- static shape info -------------------------------------------------

    def packed_action_count(self) -> int:
        """Static upper bound on actions per state (dense action ids)."""
        raise NotImplementedError

    # -- traceable transition relation ------------------------------------

    def packed_init_states(self) -> PackedState:
        """All initial states, stacked along a leading batch axis."""
        raise NotImplementedError

    def packed_step(
        self, state: PackedState, action_id: jax.Array
    ) -> Tuple[PackedState, jax.Array]:
        """One unbatched transition: ``(state, action_id) -> (next, valid)``.

        ``valid`` is a scalar bool: False when the action's guard does not
        hold in ``state`` (the action would not have been enumerated by the
        host model) or when the transition is a pruned no-op (the host
        ``next_state`` returned None). Checkers vmap this over
        frontier × action grids, so it must be jax-traceable with no
        data-dependent python control flow.
        """
        raise NotImplementedError

    def packed_expand(
        self, state: PackedState
    ) -> Tuple[PackedState, jax.Array]:
        """All ``packed_action_count()`` candidates of one state, stacked
        along a leading action axis: ``state -> (candidates, valid)``.

        The checkers' wave kernels call THIS (vmapped over the frontier),
        not ``packed_step`` — the default below is exactly a vmap of
        ``packed_step`` over the action axis, but models whose actions
        fall into structurally different classes can override it with
        specialized per-class expansion. Under vmap, ``lax.cond``/
        ``lax.switch`` inside a generic step execute EVERY branch for
        every lane, so a step that dispatches over K action classes pays
        all K class bodies per candidate; a per-class expansion pays each
        body only on its own class's slice of the grid
        (``PackedActorModel.packed_expand`` — 92% of the raft-5 wave was
        this overhead). Candidate order must match ``packed_step``'s
        action ids; equivalence on valid lanes is pinned by
        ``tests/test_packed_expand.py``.
        """
        import jax
        import jax.numpy as jnp

        aids = jnp.arange(self.packed_action_count(), dtype=jnp.int32)
        return jax.vmap(lambda a: self.packed_step(state, a))(aids)

    def packed_conditions(self) -> List[Callable[[PackedState], jax.Array]]:
        """Traceable predicates aligned with ``properties()`` (same order).

        Each maps one unbatched packed state to a scalar bool.
        """
        raise NotImplementedError

    def packed_antecedents(self):
        """OPTIONAL traceable antecedent predicates aligned 1:1 with
        ``properties()`` (``None`` entries for properties without one) —
        the device analog of ``Property.antecedent``. The coverage ledger
        (``telemetry/coverage.py``) counts antecedent-true frontier
        states per ``always`` property so vacuous passes (the guard of an
        implication-shaped invariant never firing) are detectable on the
        device path too. Never consulted outside coverage mode."""
        return [None] * len(self.packed_conditions())

    def packed_action_labels(self) -> List[str]:
        """OPTIONAL human-readable labels for the dense action ids
        ``0..packed_action_count()`` — the coverage ledger's per-action
        axis (``<prefix>.coverage.action_fired.<label>`` counters, the
        Explorer's per-action bar view, ``scripts/coverage_report.py``'s
        action table). Defaults to ``action_<id>``."""
        return [f"action_{i}" for i in range(self.packed_action_count())]

    def packed_within_boundary(self, state: PackedState) -> jax.Array:
        """Traceable analog of ``within_boundary`` (scalar bool)."""
        import jax.numpy as jnp

        return jnp.bool_(True)

    def packed_fingerprint_view(self, state: PackedState) -> PackedState:
        """The sub-pytree of ``state`` that participates in fingerprints.

        Defaults to the whole state. Models with hash-excluded components
        override this — e.g. actor systems exclude crash flags, mirroring
        the host/reference state hash
        (``/root/reference/src/actor/model_state.rs:86-97``).
        """
        return state

    def packed_fingerprint(self, state: PackedState):
        """(hi, lo) uint32 device fingerprint of one packed state — THE
        fingerprint definition every checker uses (wave dedup, replay,
        shard routing, checkpoints). Defaults to the word-serial murmur
        over ``packed_fingerprint_view``; models with component structure
        override it with a component-hash scheme whose per-candidate cost
        is the *delta*, not the state width
        (``PackedActorModel.packed_fingerprint``). Changing a model's
        scheme changes its visited-key space: ``FP_SCHEME`` plus the
        packed-model digest guard checkpoints against mixing."""
        from ..ops.fingerprint import fingerprint_state

        return fingerprint_state(self.packed_fingerprint_view(state))

    def packed_expand_fps(self, state: PackedState):
        """OPTIONAL fast path: fingerprints + validity of all
        ``packed_action_count()`` children of one state — WITHOUT
        materializing the children. Returns ``(hi, lo, valid)``, each of
        shape ``(A,)``, where ``(hi, lo)`` must equal
        ``packed_fingerprint(child_a)`` exactly on every valid lane and
        ``valid`` must equal ``packed_expand``'s validity AND'd with
        ``packed_within_boundary`` of the child.

        This is the byte-diet half of the wave pipeline: the checkers'
        fps wave dedups on these fingerprints and only materializes the
        lanes that survive (``packed_take``), so candidate states never
        round-trip through HBM. Models signal support by implementing
        both this and ``packed_take``; equivalence with the materializing
        path is pinned by ``tests/test_expand_fps.py``."""
        raise NotImplementedError

    def packed_take(self, state: PackedState, action_id) -> PackedState:
        """OPTIONAL companion to ``packed_expand_fps``: materializes the
        single child ``action_id`` of ``state`` (the post-dedup winners
        only — called on a fraction of the candidate grid). Must produce
        exactly ``packed_step``'s outcome state on valid actions; validity
        itself was already established by ``packed_expand_fps``."""
        raise NotImplementedError

    def packed_expand_fps_supported(self) -> bool:
        """Whether the fps hooks above are SAFE for this model instance —
        implementations can veto the fps wave at runtime even though the
        class provides the hooks (e.g. ``PackedActorModel`` refuses when a
        codec customizes ``packed_within_boundary`` without the per-row
        decomposition the fps path needs). Checkers consult this before
        auto-selecting the fps wave; forcing ``expand_fps=True`` against a
        veto is an error."""
        return True

    # -- symmetry (optional) -----------------------------------------------
    #
    # Device symmetry reduction is *orbit-proper*: the dedup key is the
    # minimum fingerprint over every actor permutation, so two states are
    # deduplicated iff they are genuinely in the same symmetry orbit. This
    # is deliberately NOT the reference's sort-based representative
    # (``src/checker/rewrite_plan.rs:81-106``): that heuristic is not a
    # canonical form (sorting keys change under id rewriting), so its
    # reduced counts depend on traversal order — measured on 2pc-5: DFS
    # order 665 (the reference's pinned number), BFS order 508, random
    # orders 707-757. A wave-BFS device checker cannot reproduce a
    # DFS-order artifact; it instead pins the canonical orbit counts
    # (2pc-5 = 314, 3-server lossy-duplicating Raft = 464), which are
    # traversal- and engine-independent and strictly stronger reductions.

    def packed_symmetry(self) -> Tuple[np.ndarray, np.ndarray]:
        """Returns the ``(new_to_old, old_to_new)`` permutation tables of
        the model's symmetry group (usually ``permutation_tables(n)``).
        Implementing this (plus ``packed_apply_permutation``) opts the
        model into device symmetry reduction."""
        raise NotImplementedError

    def packed_apply_permutation(
        self, state: PackedState, new_to_old: jax.Array, old_to_new: jax.Array
    ) -> PackedState:
        """Traceable group action: applies one permutation row to a packed
        state (gather index-keyed arrays by ``new_to_old``; rewrite embedded
        actor ids through ``old_to_new``). Order-insensitive components need
        NO re-canonicalization: the fingerprint view hashes them with a
        commutative multiset digest (``ops.fingerprint.multiset_digest``),
        so slot order never reaches the key."""
        raise NotImplementedError

    def packed_representative(self, state: PackedState) -> PackedState:
        """Optional traceable CUSTOM canonical form (the device analog of
        the reference's user-defined ``Representative``,
        ``src/checker/representative.rs:65-68``). When a checker is built
        with ``.symmetry_fn(custom)``, the device dedup key is the
        fingerprint of this state — the user guarantees it canonicalizes
        exactly the equivalence their host ``symmetry_fn`` quotients by
        (same-partition, like any Representative: unsound forms over- or
        under-merge and the host/device parity tests will diverge). The
        full-group ``.symmetry()`` path never calls this — it uses the
        orbit-proper WL/orbit-minimum keys."""
        raise NotImplementedError

    def packed_refine_colors(
        self, state: PackedState, colors: jax.Array
    ) -> jax.Array:
        """One round of equivariant per-actor color refinement (optional —
        the Weisfeiler-Leman-style fast path for device symmetry keys).

        Takes the (n,) uint32 color vector of the previous round (all-zero
        initially) and returns a refined (n,) uint32 vector where each
        actor's new color is a hash of its OWN id-free data plus the colors
        of the actors it references (votes, leader hints, envelope
        endpoints, …). The checkers iterate this to a stable partition,
        sort actors by final color to obtain a candidate canonical
        permutation, and verify remaining ties are genuine automorphisms —
        falling back to the exact ``n!`` orbit-minimum for any state where
        verification fails. Cost: ~``n`` fingerprint passes per state
        instead of ``n!``.

        MUST be equivariant: for any actor permutation ``s`` with action
        ``sigma``, ``refine(sigma(state), sigma(colors)) ==
        sigma(refine(state, colors))`` — i.e. depend on actor indices only
        through gathered values, never on absolute positions. A
        non-equivariant hook silently splits orbits (counts over-report);
        the orbit-count parity tests are the guard. Verification-or-
        fallback covers the other failure direction (under-separation)
        exactly, so a WEAK hook only costs speed, never correctness.
        """
        raise NotImplementedError

    # -- host interop ------------------------------------------------------

    def pack_state(self, host_state: Any) -> PackedState:
        """Packs one host state into (numpy/jax) arrays."""
        raise NotImplementedError

    def unpack_state(self, packed: PackedState) -> Any:
        """Unpacks one packed state (concrete arrays) into a host state."""
        raise NotImplementedError
