"""The core model abstraction: nondeterministic transition systems + properties.

Reference: ``Model`` trait at ``/root/reference/src/lib.rs:156-255``,
``Property``/``Expectation`` at ``:262-326``.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Any, Callable, Generic, List, Optional, Tuple, TypeVar

State = TypeVar("State")
Action = TypeVar("Action")


class Expectation(Enum):
    """Whether a property is always, eventually, or sometimes true."""

    ALWAYS = "always"
    EVENTUALLY = "eventually"
    SOMETIMES = "sometimes"


@dataclass(frozen=True)
class Property(Generic[State]):
    """A named predicate over (model, state).

    - ``always``: safety invariant; the checker seeks a counterexample.
    - ``sometimes``: reachability; the checker seeks an example.
    - ``eventually``: liveness (acyclic paths only — matching the reference's
      documented false-negative on cycles/DAG joins,
      ``/root/reference/src/lib.rs:278-287`` and ``src/checker/bfs.rs:285-305``);
      the checker seeks a counterexample path ending in a terminal state.

    ``antecedent`` (optional, ``always`` only) declares the guard of an
    implication-shaped invariant (``antecedent => consequent``): the
    coverage ledger counts the states where it held, so a run whose
    antecedent never fired is reported as a *vacuous* pass instead of a
    silent green (TLC's coverage statistics make the same distinction).
    It never changes checking semantics — only observability.
    """

    expectation: Expectation
    name: str
    condition: Callable[[Any, Any], bool]
    antecedent: Optional[Callable[[Any, Any], bool]] = None

    @staticmethod
    def always(
        name: str,
        condition: Callable[[Any, Any], bool],
        antecedent: Optional[Callable[[Any, Any], bool]] = None,
    ) -> "Property":
        return Property(Expectation.ALWAYS, name, condition, antecedent)

    @staticmethod
    def eventually(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        return Property(Expectation.EVENTUALLY, name, condition)

    @staticmethod
    def sometimes(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        return Property(Expectation.SOMETIMES, name, condition)


class Model(Generic[State, Action]):
    """The primary abstraction: implementations model a nondeterministic
    system's evolution.

    Subclasses implement ``init_states``, ``actions``, ``next_state`` and
    optionally ``properties``/``within_boundary``/display hooks.

    Reference: ``/root/reference/src/lib.rs:156-255``.
    """

    def init_states(self) -> List[State]:
        """Returns the initial possible states."""
        raise NotImplementedError

    def actions(self, state: State, actions: List[Action]) -> None:
        """Collects the subsequent possible actions based on a previous state."""
        raise NotImplementedError

    def next_state(self, last_state: State, action: Action) -> Optional[State]:
        """Converts a previous state and action to a resulting state.

        ``None`` indicates that the action does not change the state (the
        transition is pruned).
        """
        raise NotImplementedError

    def format_action(self, action: Action) -> str:
        return repr(action)

    def format_step(self, last_state: State, action: Action) -> Optional[str]:
        next_state = self.next_state(last_state, action)
        return None if next_state is None else repr(next_state)

    def as_svg(self, path) -> Optional[str]:
        """An SVG representation of a ``Path`` for this model (Explorer)."""
        return None

    def next_steps(self, last_state: State) -> List[Tuple[Action, State]]:
        """The (action, state) pairs that follow a particular state."""
        actions: List[Action] = []
        self.actions(last_state, actions)
        steps = []
        for action in actions:
            state = self.next_state(last_state, action)
            if state is not None:
                steps.append((action, state))
        return steps

    def next_states(self, last_state: State) -> List[State]:
        """The states that follow a particular state."""
        actions: List[Action] = []
        self.actions(last_state, actions)
        states = []
        for action in actions:
            state = self.next_state(last_state, action)
            if state is not None:
                states.append(state)
        return states

    def properties(self) -> List[Property]:
        return []

    def property(self, name: str) -> Property:
        """Looks up a property by name. Raises if the property does not exist."""
        for p in self.properties():
            if p.name == name:
                return p
        available = [p.name for p in self.properties()]
        raise KeyError(f"Unknown property. requested={name}, available={available}")

    def within_boundary(self, state: State) -> bool:
        """Whether a state is within the state space that should be checked."""
        return True

    def checker(self) -> "CheckerBuilder":
        from ..checker.builder import CheckerBuilder

        return CheckerBuilder(self)


class FnModel(Model):
    """Wraps ``fn(prev_state | None, next_states: list)`` as a Model, for
    one-liner models in tests (reference: blanket impl at
    ``/root/reference/src/test_util.rs:119-137``).

    When ``prev_state`` is None the function should append init states;
    otherwise it should append successor states. Every distinct successor
    state is its own action (the action *is* the state).
    """

    def __init__(self, fn: Callable[[Optional[Any], List[Any]], None]):
        self.fn = fn

    def init_states(self):
        states: List[Any] = []
        self.fn(None, states)
        return states

    def actions(self, state, actions):
        states: List[Any] = []
        self.fn(state, states)
        actions.extend(states)

    def next_state(self, last_state, action):
        return action
