"""Per-evaluated-state visitor callbacks.

Reference: ``/root/reference/src/checker/visitor.rs``.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, List, Set, TypeVar

from .path import Path

State = TypeVar("State")
Action = TypeVar("Action")


class CheckerVisitor:
    """Receives the full ``Path`` for every state the checker evaluates."""

    def visit(self, model, path: Path) -> None:
        raise NotImplementedError


class FnVisitor(CheckerVisitor):
    """Wraps any ``fn(path)`` or ``fn(model, path)`` callable as a visitor."""

    def __init__(self, fn: Callable):
        self._fn = fn
        try:
            import inspect

            self._arity = len(inspect.signature(fn).parameters)
        except (TypeError, ValueError):
            self._arity = 1

    def visit(self, model, path: Path) -> None:
        if self._arity >= 2:
            self._fn(model, path)
        else:
            self._fn(path)


class PathRecorder(CheckerVisitor, Generic[State, Action]):
    """Records the set of all visited paths."""

    def __init__(self):
        self._lock = threading.Lock()
        self.paths: Set[Path] = set()

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self.paths.add(path)


class StateRecorder(CheckerVisitor, Generic[State]):
    """Records the sequence of last-states of visited paths (i.e. the states
    in visitation order)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.states: List[State] = []

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self.states.append(path.last_state())
