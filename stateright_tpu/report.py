"""Progress reporting during model checking.

Reference: ``/root/reference/src/report.rs``. The exact output strings
(``Checking. states=..``, ``Done. states=.., sec=..``,
``Discovered "name" example Path[n]``, ``Fingerprint path: ..``) are part of
the compatibility surface — golden-tested and grepped by bench harnesses.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import IO, Dict, Optional


@dataclass
class ReportData:
    total_states: int
    unique_states: int
    max_depth: int
    duration_secs: float
    done: bool


@dataclass
class ReportDiscovery:
    path: "Path"
    classification: str  # "example" | "counterexample"


class Reporter:
    def report_checking(self, data: ReportData) -> None:
        raise NotImplementedError

    def report_discoveries(self, discoveries: Dict[str, ReportDiscovery]) -> None:
        raise NotImplementedError

    def report_undiscovered(self, properties) -> None:
        """Called once at run end (completed runs only) with the
        sometimes/eventually properties that have NO discovery, so a
        vacuous pass — a ``sometimes`` never witnessed — is visible even
        without the coverage ledger (upstream-parity: see MIGRATING.md).
        Default no-op keeps existing reporters source-compatible."""

    def report_liveness(self, inconclusive=(), skipped_crashed=False,
                        ) -> None:
        """Liveness-pass honesty lines: properties the bounded host
        post-pass could not certify within its budget, and the
        crashed-run warning (a missing counterexample must never be
        mistaken for certified absence). Default no-op keeps existing
        reporters source-compatible."""

    def report_truncation(self, overflows: int) -> None:
        """Called once at run end (simulation backends) when walks were
        silently aborted by a trace-buffer overflow — truncation must
        never be mistaken for absence of discoveries. Default no-op
        keeps existing reporters source-compatible."""

    def report_config_notes(self, notes) -> None:
        """Called once per report with backend configuration adjustments
        the checker made silently on the user's behalf (e.g. the
        tile-sweep kernels rounding ``table_capacity`` up to a
        tile-aligned power of two) — an adjusted run must never read as
        the run that was asked for. Default no-op keeps existing
        reporters source-compatible."""

    def delay(self) -> float:
        """Seconds between progress reports."""
        return 1.0


class WriteReporter(Reporter):
    def __init__(self, writer: IO[str]):
        self.writer = writer

    def report_checking(self, data: ReportData) -> None:
        if data.done:
            self.writer.write(
                f"Done. states={data.total_states}, unique={data.unique_states}, "
                f"depth={data.max_depth}, sec={int(data.duration_secs)}\n"
            )
        else:
            self.writer.write(
                f"Checking. states={data.total_states}, "
                f"unique={data.unique_states}, depth={data.max_depth}\n"
            )

    def report_discoveries(self, discoveries) -> None:
        for name in sorted(discoveries):
            discovery = discoveries[name]
            self.writer.write(
                f'Discovered "{name}" {discovery.classification} {discovery.path}'
            )
            self.writer.write(f"Fingerprint path: {discovery.path.encode()}\n")

    def report_undiscovered(self, properties) -> None:
        # Golden-surface extension (PR 9): one line per undiscovered
        # sometimes/eventually property. For "sometimes" this is the
        # vacuity warning (an example was sought and never found); for
        # "eventually" it is the explicit all-clear.
        for p in sorted(properties, key=lambda p: p.name):
            kind = getattr(p.expectation, "value", str(p.expectation))
            self.writer.write(
                f'Property "{p.name}" not discovered ({kind})\n'
            )

    def report_liveness(self, inconclusive=(), skipped_crashed=False,
                        ) -> None:
        for name in sorted(inconclusive):
            self.writer.write(
                f'Liveness "{name}" inconclusive '
                "(host post-pass budget exhausted; absence NOT "
                "certified)\n"
            )
        if skipped_crashed:
            self.writer.write(
                "Liveness pass skipped: run crashed; absence of "
                "counterexamples NOT certified\n"
            )

    def report_truncation(self, overflows: int) -> None:
        self.writer.write(
            f"Warning: {overflows} walk(s) truncated at the trace "
            "buffer (raise max_trace_len); absence of discoveries on "
            "those walks is NOT evidence\n"
        )

    def report_config_notes(self, notes) -> None:
        for note in notes:
            self.writer.write(f"Note: {note}\n")


class TelemetryReporter(Reporter):
    """Renders telemetry metrics snapshots alongside (not instead of) an
    inner reporter's output. The golden ``WriteReporter`` strings are a
    compatibility surface, so this reporter never alters them: it
    delegates every callback to the wrapped reporter verbatim, then — on
    the final (done) report — writes one ``Telemetry <json>`` line from
    the metrics registry. Wrap-free use (``inner=None``) emits only the
    telemetry line.

        checker.join_and_report(
            TelemetryReporter(sys.stdout, inner=WriteReporter(sys.stdout))
        )
    """

    def __init__(self, writer: IO[str], inner: Optional[Reporter] = None,
                 registry=None):
        self.writer = writer
        self.inner = inner
        if registry is None:
            from .telemetry import metrics_registry

            registry = metrics_registry()
        self.registry = registry

    def report_checking(self, data: ReportData) -> None:
        if self.inner is not None:
            self.inner.report_checking(data)
        if data.done:
            snap = self.registry.snapshot()
            self.writer.write(
                "Telemetry " + json.dumps(snap, sort_keys=True, default=str)
                + "\n"
            )

    def report_discoveries(self, discoveries) -> None:
        if self.inner is not None:
            self.inner.report_discoveries(discoveries)

    def report_undiscovered(self, properties) -> None:
        if self.inner is not None:
            self.inner.report_undiscovered(properties)

    def report_liveness(self, inconclusive=(), skipped_crashed=False,
                        ) -> None:
        if self.inner is not None:
            self.inner.report_liveness(
                inconclusive=inconclusive,
                skipped_crashed=skipped_crashed,
            )

    def report_truncation(self, overflows: int) -> None:
        if self.inner is not None:
            self.inner.report_truncation(overflows)

    def report_config_notes(self, notes) -> None:
        if self.inner is not None:
            self.inner.report_config_notes(notes)

    def delay(self) -> float:
        return self.inner.delay() if self.inner is not None else 1.0
