"""Progress reporting during model checking.

Reference: ``/root/reference/src/report.rs``. The exact output strings
(``Checking. states=..``, ``Done. states=.., sec=..``,
``Discovered "name" example Path[n]``, ``Fingerprint path: ..``) are part of
the compatibility surface — golden-tested and grepped by bench harnesses.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import IO, Dict


@dataclass
class ReportData:
    total_states: int
    unique_states: int
    max_depth: int
    duration_secs: float
    done: bool


@dataclass
class ReportDiscovery:
    path: "Path"
    classification: str  # "example" | "counterexample"


class Reporter:
    def report_checking(self, data: ReportData) -> None:
        raise NotImplementedError

    def report_discoveries(self, discoveries: Dict[str, ReportDiscovery]) -> None:
        raise NotImplementedError

    def delay(self) -> float:
        """Seconds between progress reports."""
        return 1.0


class WriteReporter(Reporter):
    def __init__(self, writer: IO[str]):
        self.writer = writer

    def report_checking(self, data: ReportData) -> None:
        if data.done:
            self.writer.write(
                f"Done. states={data.total_states}, unique={data.unique_states}, "
                f"depth={data.max_depth}, sec={int(data.duration_secs)}\n"
            )
        else:
            self.writer.write(
                f"Checking. states={data.total_states}, "
                f"unique={data.unique_states}, depth={data.max_depth}\n"
            )

    def report_discoveries(self, discoveries) -> None:
        for name in sorted(discoveries):
            discovery = discoveries[name]
            self.writer.write(
                f'Discovered "{name}" {discovery.classification} {discovery.path}'
            )
            self.writer.write(f"Fingerprint path: {discovery.path.encode()}\n")
