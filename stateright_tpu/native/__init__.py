"""Native host-runtime components (C++ via ctypes).

The compute path is JAX/XLA; the host runtime around it is native where the
reference's is (its checker bookkeeping lives in native concurrent maps,
``/root/reference/src/checker/bfs.rs:28-29``). Currently: ``fp_store``, the
parent-pointer/visited bookkeeping used by the device checkers for path
reconstruction and checkpointing.

The shared library builds on first use with the toolchain's ``g++`` (no
packaging step: ``pip install`` is unavailable in the target image) and
falls back to a pure-Python store if compilation is impossible.
"""

from __future__ import annotations

import ctypes
import hashlib
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_DIR = Path(__file__).resolve().parent
_SRC = _DIR / "fp_store.cc"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _lib_path() -> Path:
    """Artifact path keyed on a content hash of the source (advisor,
    round 4): mtime comparisons are meaningless after a git clone (git
    does not preserve mtimes), and a content key means an edited .cc can
    never silently load a stale binary."""
    digest = hashlib.blake2b(_SRC.read_bytes(), digest_size=8).hexdigest()
    return _DIR / "_build" / f"libfp_store-{digest}.so"


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            lib_file = _lib_path()
            if not lib_file.exists():
                lib_file.parent.mkdir(exist_ok=True)
                subprocess.run(
                    [
                        "g++",
                        "-O3",
                        "-shared",
                        "-fPIC",
                        "-std=c++17",
                        str(_SRC),
                        "-o",
                        str(lib_file),
                    ],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            lib = ctypes.CDLL(str(lib_file))
        except (OSError, subprocess.SubprocessError):
            _build_failed = True
            return None
        u64 = ctypes.c_uint64
        p64 = ctypes.POINTER(u64)
        lib.fps_new.restype = ctypes.c_void_p
        lib.fps_new.argtypes = [u64]
        lib.fps_free.argtypes = [ctypes.c_void_p]
        lib.fps_size.restype = u64
        lib.fps_size.argtypes = [ctypes.c_void_p]
        lib.fps_insert_batch.restype = u64
        lib.fps_insert_batch.argtypes = [ctypes.c_void_p, p64, p64, u64]
        lib.fps_contains.restype = ctypes.c_int
        lib.fps_contains.argtypes = [ctypes.c_void_p, u64]
        lib.fps_get_parent.restype = u64
        lib.fps_get_parent.argtypes = [ctypes.c_void_p, u64]
        lib.fps_chain.restype = ctypes.c_int64
        lib.fps_chain.argtypes = [ctypes.c_void_p, u64, p64, u64]
        lib.fps_export.restype = u64
        lib.fps_export.argtypes = [ctypes.c_void_p, p64, p64, u64]
        _lib = lib
        return _lib


def _as_u64_buf(arr: np.ndarray):
    arr = np.ascontiguousarray(arr, dtype=np.uint64)
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


class NativeFingerprintStore:
    """u64 fingerprint → parent fingerprint map (0 parent = root).

    Batch inserts are first-writer-wins, matching BFS shortest-path parent
    recording. All operations serialize on an internal lock: ctypes calls
    release the GIL, and a concurrent ``insert_batch`` growth would free
    the buffers a reader is probing."""

    def __init__(self, capacity_hint: int = 1 << 16):
        lib = _load()
        if lib is None:
            raise RuntimeError("native fp_store unavailable")
        self._lib = lib
        self._ptr = lib.fps_new(ctypes.c_uint64(capacity_hint))
        if not self._ptr:
            raise MemoryError("fps_new: allocation failed")
        self._oplock = threading.Lock()

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.fps_free(ptr)
            self._ptr = None

    def __len__(self) -> int:
        with self._oplock:
            return int(self._lib.fps_size(self._ptr))

    def insert_batch(self, children: np.ndarray, parents: np.ndarray) -> int:
        children, cbuf = _as_u64_buf(children)
        parents, pbuf = _as_u64_buf(parents)
        assert children.shape == parents.shape
        with self._oplock:
            fresh = int(
                self._lib.fps_insert_batch(
                    self._ptr, cbuf, pbuf, ctypes.c_uint64(children.shape[0])
                )
            )
        if fresh == 0xFFFFFFFFFFFFFFFF:
            raise MemoryError("fp_store: table growth allocation failed")
        return fresh

    def __contains__(self, fp: int) -> bool:
        with self._oplock:
            return bool(self._lib.fps_contains(self._ptr, ctypes.c_uint64(fp)))

    def parent(self, fp: int) -> Optional[int]:
        with self._oplock:
            p = int(self._lib.fps_get_parent(self._ptr, ctypes.c_uint64(fp)))
        return p or None

    def chain(self, fp: int) -> list:
        """Root-first fingerprint chain ending at ``fp``; raises KeyError
        for unknown fingerprints."""
        cap = 1 << 10
        while True:
            out = np.empty((cap,), np.uint64)
            _, obuf = _as_u64_buf(out)
            with self._oplock:
                n = int(
                    self._lib.fps_chain(
                        self._ptr,
                        ctypes.c_uint64(fp),
                        obuf,
                        ctypes.c_uint64(cap),
                    )
                )
            if n == -1:
                raise KeyError(fp)
            if n == -2:
                cap *= 16
                continue
            return out[:n].tolist()

    def export(self):
        """All (children, parents) pairs as two u64 arrays."""
        with self._oplock:
            n = int(self._lib.fps_size(self._ptr))
            children = np.empty((n,), np.uint64)
            parents = np.empty((n,), np.uint64)
            _, cbuf = _as_u64_buf(children)
            _, pbuf = _as_u64_buf(parents)
            wrote = int(
                self._lib.fps_export(self._ptr, cbuf, pbuf, ctypes.c_uint64(n))
            )
        return children[:wrote], parents[:wrote]


class PyFingerprintStore:
    """Pure-Python fallback with the same surface."""

    def __init__(self, capacity_hint: int = 0):
        self._map = {}

    def __len__(self) -> int:
        return len(self._map)

    def insert_batch(self, children, parents) -> int:
        fresh = 0
        m = self._map
        for c, p in zip(
            np.asarray(children, np.uint64).tolist(),
            np.asarray(parents, np.uint64).tolist(),
        ):
            if c and c not in m:
                m[c] = p
                fresh += 1
        return fresh

    def __contains__(self, fp: int) -> bool:
        return fp in self._map

    def parent(self, fp: int):
        return self._map.get(fp) or None

    def chain(self, fp: int) -> list:
        if fp not in self._map:
            raise KeyError(fp)
        out = []
        cur = fp
        while cur:
            out.append(cur)
            cur = self._map.get(cur, 0)
        return out[::-1]

    def export(self):
        children = np.fromiter(self._map.keys(), np.uint64, len(self._map))
        parents = np.fromiter(self._map.values(), np.uint64, len(self._map))
        return children, parents


def make_fingerprint_store(capacity_hint: int = 1 << 16):
    """The native store when buildable, else the Python fallback."""
    try:
        return NativeFingerprintStore(capacity_hint)
    except RuntimeError:
        return PyFingerprintStore(capacity_hint)


__all__ = [
    "NativeFingerprintStore",
    "PyFingerprintStore",
    "make_fingerprint_store",
]
