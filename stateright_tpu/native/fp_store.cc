// Native fingerprint store: the host-runtime half of the device checkers.
//
// The reference keeps its visited set / parent map in native concurrent
// hash maps (DashMap<Fingerprint, Option<Fingerprint>>,
// /root/reference/src/checker/bfs.rs:28-29). In this framework the *device*
// owns the visited set; what remains on the host is the parent-pointer map
// used for TLC-style path reconstruction and checkpointing — this file is
// its native implementation (open addressing over u64 fingerprints, batch
// ingestion straight from numpy buffers, chain walking in C).
//
// Keys are nonzero u64 fingerprints (0 is the empty-slot sentinel; device
// fingerprints are never (0,0) — see stateright_tpu/ops/fingerprint.py).
// Parent 0 encodes "initial state". Single-writer use; readers may query
// between batch inserts (the Python side serializes access).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

struct Store {
  uint64_t *keys;     // 0 = empty
  uint64_t *parents;  // parallel to keys
  uint64_t capacity;  // power of two
  uint64_t size;
};

uint64_t hash_u64(uint64_t x) {
  // splitmix64 finalizer: well-mixed index bits from already-random keys.
  x ^= x >> 30;
  x *= 0xbf58476d1ce4e5b9ULL;
  x ^= x >> 27;
  x *= 0x94d049bb133111ebULL;
  x ^= x >> 31;
  return x;
}

uint64_t pow2ceil(uint64_t n) {
  uint64_t c = 1;
  while (c < n) c <<= 1;
  return c;
}

// Returns 0 on success, -1 on allocation failure (store left intact — the
// store holds every visited fingerprint, so exhausting host memory here is
// plausible and must surface as an error, not a segfault).
int grow(Store *s, uint64_t min_capacity) {
  uint64_t new_cap = s->capacity;
  while (new_cap < min_capacity || s->size * 10 >= new_cap * 7) new_cap <<= 1;
  uint64_t *nk = (uint64_t *)calloc(new_cap, sizeof(uint64_t));
  uint64_t *np = (uint64_t *)calloc(new_cap, sizeof(uint64_t));
  if (!nk || !np) {
    free(nk);
    free(np);
    return -1;
  }
  uint64_t mask = new_cap - 1;
  for (uint64_t i = 0; i < s->capacity; i++) {
    uint64_t k = s->keys[i];
    if (!k) continue;
    uint64_t j = hash_u64(k) & mask;
    while (nk[j]) j = (j + 1) & mask;
    nk[j] = k;
    np[j] = s->parents[i];
  }
  free(s->keys);
  free(s->parents);
  s->keys = nk;
  s->parents = np;
  s->capacity = new_cap;
  return 0;
}

// Returns the slot of key, or the empty slot where it would go.
uint64_t probe(const Store *s, uint64_t key) {
  uint64_t mask = s->capacity - 1;
  uint64_t j = hash_u64(key) & mask;
  while (s->keys[j] && s->keys[j] != key) j = (j + 1) & mask;
  return j;
}

}  // namespace

extern "C" {

// Returns NULL on allocation failure.
void *fps_new(uint64_t capacity_hint) {
  Store *s = (Store *)malloc(sizeof(Store));
  if (!s) return nullptr;
  s->capacity = pow2ceil(capacity_hint < 64 ? 64 : capacity_hint);
  s->keys = (uint64_t *)calloc(s->capacity, sizeof(uint64_t));
  s->parents = (uint64_t *)calloc(s->capacity, sizeof(uint64_t));
  if (!s->keys || !s->parents) {
    free(s->keys);
    free(s->parents);
    free(s);
    return nullptr;
  }
  s->size = 0;
  return s;
}

void fps_free(void *p) {
  Store *s = (Store *)p;
  free(s->keys);
  free(s->parents);
  free(s);
}

uint64_t fps_size(const void *p) { return ((const Store *)p)->size; }

// First-writer-wins batch insert (BFS: the first recorded parent is the
// shortest-path parent). Returns the number of new keys, or UINT64_MAX if
// growing the table failed (out of memory; no keys were inserted).
uint64_t fps_insert_batch(void *p, const uint64_t *children,
                          const uint64_t *parents, uint64_t n) {
  Store *s = (Store *)p;
  if ((s->size + n) * 10 >= s->capacity * 7) {
    if (grow(s, pow2ceil(s->size + n) * 2) != 0) return ~0ULL;
  }
  uint64_t fresh = 0;
  for (uint64_t i = 0; i < n; i++) {
    uint64_t key = children[i];
    if (!key) continue;
    uint64_t j = probe(s, key);
    if (!s->keys[j]) {
      s->keys[j] = key;
      s->parents[j] = parents ? parents[i] : 0;
      s->size++;
      fresh++;
    }
  }
  return fresh;
}

int fps_contains(const void *p, uint64_t key) {
  const Store *s = (const Store *)p;
  return s->keys[probe(s, key)] == key;
}

// Parent of key; 0 for roots and unknown keys (use fps_contains to
// distinguish).
uint64_t fps_get_parent(const void *p, uint64_t key) {
  const Store *s = (const Store *)p;
  uint64_t j = probe(s, key);
  return s->keys[j] == key ? s->parents[j] : 0;
}

// Walks parent pointers from fp to a root, writing the chain root-first
// into out (capacity cap). A dangling (unknown) parent terminates the
// chain but is included in it, matching the Python fallback. Returns the
// chain length, -1 if fp itself is unknown, or -2 if cap is too small
// (call again with a larger buffer).
int64_t fps_chain(const void *p, uint64_t fp, uint64_t *out, uint64_t cap) {
  const Store *s = (const Store *)p;
  if (s->keys[probe(s, fp)] != fp) return -1;
  uint64_t len = 0;
  uint64_t cur = fp;
  while (cur) {
    len++;
    uint64_t j = probe(s, cur);
    cur = s->keys[j] == cur ? s->parents[j] : 0;
  }
  if (len > cap) return -2;
  // Second pass: write root-first with the same transition rule.
  cur = fp;
  uint64_t i = len;
  while (cur) {
    out[--i] = cur;
    uint64_t j = probe(s, cur);
    cur = s->keys[j] == cur ? s->parents[j] : 0;
  }
  return (int64_t)len;
}

// Exports all (child, parent) pairs; returns the count written (<= cap).
uint64_t fps_export(const void *p, uint64_t *children, uint64_t *parents,
                    uint64_t cap) {
  const Store *s = (const Store *)p;
  uint64_t n = 0;
  for (uint64_t i = 0; i < s->capacity && n < cap; i++) {
    if (!s->keys[i]) continue;
    children[n] = s->keys[i];
    parents[n] = s->parents[i];
    n++;
  }
  return n;
}

}  // extern "C"
