"""Swarm verification engine: device-width randomized walks for state
spaces beyond the store.

``checker/tpu_simulation.py`` already walks N vmapped lanes, but it is
host-paced: small ``steps_per_call`` round trips, no visited sampling,
no restart dedup, no preemption, no service integration. This module is
the GPUexplore-style swarm mode (PAPERS: "On the Scalability of the
GPUexplore Explicit-State Model Checker") built for state spaces the
PR 5 tiered store cannot enumerate:

- **One long fused scan per wave.** The entire walk loop — per-walk
  threefry PRNG streams (``fold_in(PRNGKey(seed), lane)``), restart /
  boundary / depth / terminal handling, per-lane cycle detection against
  the walk's own trace buffer, property evaluation, and per-property
  discovery capture — runs inside one jitted ``lax.scan`` of
  ``wave_steps`` steps (thousands, not 64). The host touches the device
  once per wave: a single stats pull.
- **A device hash-table sample of walk fingerprints** (``ops/hashset``,
  the duplicate-tolerant scatter-claim insert): every sampled step and
  every restart claim-inserts its fingerprint, which (a) dedups restarts
  (``swarm.restarts_deduped`` counts walks re-entering already-sampled
  states) and (b) yields an honest unique-coverage *estimate* —
  ``unique_state_count()`` is the number of distinct sampled
  fingerprints, reported as a lower bound once the fixed-capacity table
  saturates (``sample_saturated``). The walk dynamics never read the
  table, so the sample is pure observation: results are bit-identical
  at any ``sample_capacity``.
- **Run-anywhere determinism.** The stop decision (every property
  discovered, or ``target_state_count`` reached) is evaluated INSIDE
  the scan and freezes the carry at the exact step it fires, so the
  same seed produces bit-identical discoveries, walk counts, and
  coverage estimates regardless of ``wave_steps`` chunking, across
  preempt/resume (the checkpoint-v3 ``swarm`` payload slice carries the
  PRNG keys and walk buffers verbatim), and packed-vs-solo (a packed
  tenant's slot computes exactly the solo carry under ``vmap``).
- **Frontier-seeded hybrid mode.** ``seeds=`` accepts a packed-state
  pool — e.g. ``frontier_seeds_from_payload`` applied to a
  budget-exhausted ``TpuBfsChecker`` preempt payload — and walk
  restarts draw from that pool instead of the init states: the
  exhaustive run maps the space it can afford, the swarm hunts beyond
  its live frontier. Seeded discoveries replay from their seed state
  (the path *fragment* past the frontier; the prefix lives in the
  exhaustive run's store).

``SwarmEngine`` is the shared multi-tenant kernel (max_tenants slots
over one stacked dispatch — walks are lane-independent, so tenant
packing is exact by ``vmap`` semantics); ``SwarmChecker`` is the solo
``Checker`` facade ``spawn_swarm`` returns; ``SwarmPackedEngine`` is
the service packer's engine (admit / step / drop / release — the
``TenantPackedEngine`` protocol).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.batch import BatchableModel
from ..core.path import Path
from ..ops.fingerprint import fp_to_int
from ..ops.hashset import hashset_insert_unsorted, hashset_new
from ..telemetry import device_step_annotation, get_tracer, metrics_registry
from ..utils.faults import TenantFaultError, fault_point
from .base import Checker
from .tpu import checkpoint_header, validate_checkpoint_header
from .tpu_simulation import (
    capture_discoveries,
    walk_kernel_surface,
    walk_lane_step,
)

__all__ = [
    "SwarmChecker",
    "SwarmEngine",
    "SwarmPackedEngine",
    "frontier_seeds_from_payload",
]

# Runtime "no cap/target" sentinels (per-tenant scalars in the carry, so
# one compiled wave serves every tenant's depth cap and state target).
_NO_CAP = np.int32(2**31 - 1)
_NO_TARGET = np.int32(-1)

# Shared wave executables across engines of one zoo configuration: the
# second same-shape swarm job (and every preempted job's next
# incarnation) compiles nothing. Keyed on the AOT namespace plus every
# shape-determining knob; entries hold the jitted stacked-wave fn.
# Bounded like the service's model cache — a long-lived service fed
# many distinct configurations must not pin executables forever.
_WAVE_FN_CACHE: Dict[tuple, object] = {}
_WAVE_FN_CACHE_MAX = 32


def frontier_seeds_from_payload(model, payload: dict):
    """Extracts the LIVE frontier states from a ``TpuBfsChecker``
    checkpoint/preempt payload as a swarm restart-seed pool (stacked
    packed states, numpy leaves). This is the hybrid handoff: a
    budget-exhausted exhaustive run's pending frontier becomes the
    swarm's restart distribution, so walks start where enumeration
    stopped instead of re-rolling the shallow region it already
    certified."""
    if payload.get("kind") not in ("tpu_bfs",):
        raise ValueError(
            f"frontier seeds need a tpu_bfs payload, got kind="
            f"{payload.get('kind')!r}"
        )
    if payload.get("model") != type(model).__name__:
        raise ValueError(
            f"payload was written by model {payload.get('model')!r}, "
            f"seeding walks of {type(model).__name__!r} would mix state "
            "spaces"
        )
    parts = []
    for chunk in payload.get("chunks", ()):
        mask = np.asarray(chunk["mask"]).astype(bool)
        if not mask.any():
            continue
        parts.append(
            jax.tree_util.tree_map(
                lambda x: np.asarray(x)[mask], chunk["states"]
            )
        )
    if not parts:
        raise ValueError(
            "payload has no live frontier lanes to seed from (the run "
            "finished; there is nothing beyond the store to hunt)"
        )
    return jax.tree_util.tree_map(
        lambda *xs: np.concatenate(xs, axis=0), *parts
    )


class _WalkKernel:
    """The pure compute core: everything the jitted wave closes over —
    model, conditions, seed pool, shapes — and NOTHING else. Kept
    separate from ``SwarmEngine`` so the shared-executable cache pins
    only this (the model and seeds it genuinely needs), never the
    engine's multi-MB device carry or its metric instruments."""

    def __init__(self, model, *, lanes, wave_steps, max_trace_len,
                 sample_capacity, sample_stride, seeds,
                 coverage_layout):
        if not isinstance(model, BatchableModel):
            raise TypeError(
                f"the swarm engine requires a BatchableModel; "
                f"{type(model).__name__} does not implement the packed "
                "protocol"
            )
        if sample_capacity & (sample_capacity - 1):
            raise ValueError("sample_capacity must be a power of two")
        self._model = model
        (
            self._properties,
            self._conditions,
            self._ebit,
            self._ebits0,
        ) = walk_kernel_surface(model)
        self._A = model.packed_action_count()
        self._P = len(self._properties)
        self._L = int(lanes)
        self._K = int(wave_steps)
        self._D = int(max_trace_len)
        self._cap = int(sample_capacity)
        self._stride = max(1, int(sample_stride))
        self._cov_layout = coverage_layout
        if coverage_layout is not None:
            try:
                ants = list(model.packed_antecedents())
            except Exception:  # noqa: BLE001 - optional hook
                ants = [None] * self._P
            self._cov_antecedents = ants
        self._fp_fn = model.packed_fingerprint

        # Restart-seed pool: the model's init states by default, or the
        # hybrid frontier pool. Closed over by the jit as a constant.
        if seeds is None:
            seeds = model.packed_init_states()
            self._seeded = False
        else:
            self._seeded = True
        self._seeds = jax.tree_util.tree_map(jnp.asarray, seeds)
        self._n_seeds = int(
            jax.tree_util.tree_leaves(self._seeds)[0].shape[0]
        )
        if self._n_seeds < 1:
            raise ValueError("the restart-seed pool is empty")
        # Host mirrors for seeded-path replay: fp -> host state of each
        # seed, so a discovery whose walk started mid-space can still be
        # replayed into a concrete Path fragment. The digest pins the
        # pool's CONTENT in cache keys and checkpoint payloads — a
        # same-shape but different pool must never be substituted (the
        # walk sequence would silently diverge).
        self._seed_host = jax.tree_util.tree_map(np.asarray, self._seeds)
        from hashlib import blake2b

        h = blake2b(digest_size=8)
        for leaf in jax.tree_util.tree_leaves(self._seed_host):
            arr = np.asarray(leaf)
            h.update(str(arr.shape).encode())
            h.update(str(arr.dtype).encode())
            h.update(arr.tobytes())
        self.seeds_digest = h.hexdigest()

    # -- carry shape ------------------------------------------------------

    def _blank_tenant(self):
        L, D, P = self._L, self._D, self._P
        inits = self._model.packed_init_states()
        return {
            "lanes": {
                "state": jax.tree_util.tree_map(
                    lambda x: jnp.zeros((L,) + x.shape[1:], x.dtype), inits
                ),
                "depth": jnp.zeros((L,), jnp.int32),
                "ebits": jnp.zeros((L,), jnp.uint32),
                "done": jnp.ones((L,), bool),  # all lanes restart on step 1
                "thi": jnp.zeros((L, D), jnp.uint32),
                "tlo": jnp.zeros((L, D), jnp.uint32),
                "key": jnp.zeros((L, 2), jnp.uint32),
            },
            "table": hashset_new(self._cap),
            "disc": {
                "found": jnp.zeros((P,), bool),
                "hi": jnp.zeros((P, D), jnp.uint32),
                "lo": jnp.zeros((P, D), jnp.uint32),
                "len": jnp.zeros((P,), jnp.int32),
            },
            "stats": {
                "step": jnp.int32(0),
                "count": jnp.int32(0),
                "max_depth": jnp.int32(0),
                "walks": jnp.int32(0),
                "restarts": jnp.int32(0),
                "restart_dups": jnp.int32(0),
                "overflow": jnp.int32(0),
                "sample_unique": jnp.int32(0),
                "sample_sat": jnp.bool_(False),
                # Free slots are born stopped: the wave freezes them.
                "stopped": jnp.bool_(True),
            },
            "depth_cap": jnp.int32(_NO_CAP),
            "target": jnp.int32(_NO_TARGET),
            **(
                {"cov": jnp.zeros((self._cov_layout.size,), jnp.int32)}
                if self._cov_layout is not None
                else {}
            ),
        }

    # -- the fused walk kernel ----------------------------------------------

    def _lane_step(self, state, depth, ebits, done, thi, tlo, key,
                   depth_cap):
        """One walk step for a single lane (vmapped over L); the body is
        the ``walk_lane_step`` core shared with ``TpuSimulationChecker``
        — the swarm passes the runtime depth cap and its restart pool,
        and consumes the truncation/restart/coverage outputs the
        simulation checker's scan drops."""
        return walk_lane_step(
            self, self._seeds, self._n_seeds, state, depth, ebits, done,
            thi, tlo, key, depth_cap,
        )

    def _tenant_step(self, c):
        """One fused step for a whole tenant (lane vmap + sample insert
        + discovery capture + in-scan stop). The stop flag freezes the
        carry exactly: chunking into waves can never change results."""
        i32 = jnp.int32
        stats = c["stats"]
        stopped = stats["stopped"]

        out = jax.vmap(
            self._lane_step, in_axes=(0, 0, 0, 0, 0, 0, 0, None)
        )(
            c["lanes"]["state"],
            c["lanes"]["depth"],
            c["lanes"]["ebits"],
            c["lanes"]["done"],
            c["lanes"]["thi"],
            c["lanes"]["tlo"],
            c["lanes"]["key"],
            c["depth_cap"],
        )

        # Sample the visited multiset: every ``sample_stride``-th step
        # plus every restart (restart dedup must never be strided away).
        sample = out["write"] & (
            ((stats["step"] % i32(self._stride)) == 0) | out["restarted"]
        )
        table, fresh, found, pending = hashset_insert_unsorted(
            c["table"], out["hi"], out["lo"], sample
        )

        # SATURATING step counter: the count is carried across waves
        # (the in-scan stop needs it), so past ~2.15B lane-steps it
        # pins at INT32_MAX instead of wrapping negative — targets are
        # validated < 2^31 at admission, so the stop logic never needs
        # the saturated range. (tpu_simulation.py dodges this by
        # zeroing per call; a fused scan cannot.)
        count_inc = stats["count"] + out["counted"].sum(dtype=i32)
        new_stats = {
            "step": stats["step"] + 1,
            "count": jnp.where(
                count_inc < stats["count"],
                jnp.int32(2**31 - 1),
                count_inc,
            ),
            "max_depth": jnp.maximum(
                stats["max_depth"], out["path_len"].max()
            ),
            "walks": stats["walks"] + out["done"].sum(dtype=i32),
            "restarts": stats["restarts"]
            + out["restarted"].sum(dtype=i32),
            "restart_dups": stats["restart_dups"]
            + (out["restarted"] & found).sum(dtype=i32),
            "overflow": stats["overflow"]
            + out["truncated"].sum(dtype=i32),
            "sample_unique": stats["sample_unique"]
            + fresh.sum(dtype=i32),
            "sample_sat": stats["sample_sat"] | pending.any(),
        }

        disc = c["disc"]
        P = self._P
        if P:
            disc = capture_discoveries(disc, out, P)
            all_found = disc["found"].all()
        else:
            all_found = jnp.bool_(False)
        target = c["target"]
        new_stats["stopped"] = all_found | (
            (target >= 0) & (new_stats["count"] >= target)
        )

        new_c = {
            "lanes": {
                k: out[k]
                for k in (
                    "state", "depth", "ebits", "done", "thi", "tlo", "key"
                )
            },
            "table": table,
            "disc": disc,
            "stats": new_stats,
            "depth_cap": c["depth_cap"],
            "target": c["target"],
        }
        if self._cov_layout is not None:
            new_c["cov"] = c["cov"] + self._cov_layout.wave_reduce(
                eval_mask=out["counted"],
                cvalid=out["cvalid"],
                fresh=out["advanced"],
                lane_action=out["choice"],
                new_depth=out["depth"],
                exercised=[
                    out["exercised"][:, i] for i in range(self._P)
                ],
            )
        # Freeze-on-stop: a stopped tenant's slot passes through
        # untouched (PRNG keys included), so results are independent of
        # how many extra wave steps the fleet runs past its stop.
        return jax.tree_util.tree_map(
            lambda old, new: jnp.where(stopped, old, new), c, new_c
        )

    def _tenant_wave(self, c):
        return jax.lax.scan(
            lambda carry, _: (self._tenant_step(carry), None),
            c,
            None,
            length=self._K,
        )[0]



class SwarmEngine:
    """The shared device kernel: ``max_tenants`` walk fleets advance in
    one stacked jitted dispatch. Tenant slots are independent lane
    blocks — admission writes a slot's carry, a wave advances every
    non-stopped slot by ``wave_steps`` fused steps, and a drop reads the
    slot back out as a checkpoint-v3 payload slice. Because slots never
    interact (separate PRNG streams, separate sample tables, per-tenant
    stop flags), a tenant's results are bit-identical solo or packed.
    """

    def __init__(
        self,
        model,
        *,
        lanes: int = 1024,
        wave_steps: int = 1024,
        max_trace_len: int = 256,
        sample_capacity: int = 1 << 15,
        sample_stride: int = 1,
        max_tenants: int = 1,
        seeds=None,
        coverage_layout=None,
        aot_cache: Optional[str] = None,
        tracer=None,
        registry=None,
    ):
        self._k = _WalkKernel(
            model, lanes=lanes, wave_steps=wave_steps,
            max_trace_len=max_trace_len,
            sample_capacity=sample_capacity,
            sample_stride=sample_stride, seeds=seeds,
            coverage_layout=coverage_layout,
        )
        k = self._k
        # Mirrored views of the kernel's static facts (one source of
        # truth; the engine adds only mutable run state on top).
        self._model = k._model
        self._properties = k._properties
        self._cov_layout = k._cov_layout
        self._fp_fn = k._fp_fn
        self._seeded = k._seeded
        self._seeds = k._seeds
        self._seed_host = k._seed_host
        self._n_seeds = k._n_seeds
        self._A, self._P = k._A, k._P
        self._L, self._K, self._D = k._L, k._K, k._D
        self._cap, self._stride = k._cap, k._stride
        self._T = max(1, int(max_tenants))
        self._tracer = tracer if tracer is not None else get_tracer()
        self._registry = (
            registry if registry is not None else metrics_registry()
        )
        self._wave_calls = 0

        # Engine-level instruments (per-tenant registries get their own
        # families from the views).
        reg = self._registry
        self._m_waves = reg.counter("swarm.wave_calls")
        self._m_steps = reg.counter("swarm.walk_steps")
        self._m_walks = reg.counter("swarm.walks_completed")
        self._m_restarts = reg.counter("swarm.restarts")
        self._m_restart_dups = reg.counter("swarm.restarts_deduped")
        self._m_overflow = reg.counter("swarm.trace_overflow")
        self._m_unique = reg.counter("swarm.unique_sample")
        self._g_sat = reg.gauge("swarm.sample_saturated")
        self._g_occ = reg.gauge("swarm.sample_occupancy")
        self._h_hit_depth = reg.histogram("swarm.hit_depth")

        self._wave_fn = self._build_wave_fn(aot_cache)
        self._carry = self._blank_carry()
        # Last pulled per-tenant stats (numpy), refreshed each wave.
        self._stats_host = jax.device_get(self._carry["stats"])
        self._disc_found_host = np.asarray(self._carry["disc"]["found"])
        self.warmup_seconds: Optional[float] = None

    # -- carry construction -------------------------------------------------

    def _blank_carry(self):
        one = self._k._blank_tenant()
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(
                x[None], (self._T,) + x.shape
            ).copy(),
            one,
        )

    def fresh_tenant_carry(self, seed: int, depth_cap=None, target=None):
        """A new tenant slot's carry: per-walk threefry streams derived
        from ``fold_in(PRNGKey(seed), lane)`` — independent of slot
        index and fleet width, which is the packed-vs-solo bit-identity
        story."""
        c = self._k._blank_tenant()
        base = jax.random.PRNGKey(int(seed))
        c["lanes"]["key"] = jax.vmap(
            lambda i: jax.random.fold_in(base, i)
        )(jnp.arange(self._L)).astype(jnp.uint32)
        c["stats"]["stopped"] = jnp.bool_(False)
        if depth_cap is not None:
            if not 0 < int(depth_cap) < 2**31:
                raise ValueError(
                    f"target_max_depth={depth_cap} out of the int32 "
                    "range the walk carry uses"
                )
            c["depth_cap"] = jnp.int32(int(depth_cap))
        if target is not None:
            if not 0 < int(target) < 2**31:
                # int32 would silently wrap a >=2^31 target negative —
                # which the in-scan stop reads as NO target at all.
                raise ValueError(
                    f"target_state_count={target} exceeds the int32 "
                    "walk counter; split the budget across resumed "
                    "runs"
                )
            c["target"] = jnp.int32(int(target))
        return c

    def write_slot(self, t: int, tenant_carry) -> None:
        self._carry = jax.tree_util.tree_map(
            lambda full, one: full.at[t].set(one), self._carry, tenant_carry
        )
        # The written slot's stats/found flags are already in
        # tenant_carry: update the host mirrors in place (fresh copies —
        # run_wave's delta baseline may still reference the old arrays)
        # instead of a fleet-wide blocking device pull per admit/drop.
        stats = {}
        for k, arr in self._stats_host.items():
            arr = np.array(arr)
            arr[t] = np.asarray(tenant_carry["stats"][k])
            stats[k] = arr
        self._stats_host = stats
        found = np.array(self._disc_found_host)
        found[t] = np.asarray(tenant_carry["disc"]["found"])
        self._disc_found_host = found

    def read_slot(self, t: int):
        return jax.tree_util.tree_map(
            lambda x: np.asarray(x[t]), self._carry
        )

    def clear_slot(self, t: int) -> None:
        self.write_slot(t, self._k._blank_tenant())

    def _build_wave_fn(self, aot_cache):
        # The cached fn closes over the KERNEL (model + seed pool —
        # never the engine's carry or instruments), so the key pins the
        # model by IDENTITY — a config digest cannot distinguish models
        # whose packed shapes match but whose transition logic differs
        # (e.g. ShardedKv guarded vs unguarded); the cache entry's
        # closure keeps the model alive so the id stays stable — and
        # the seeds by CONTENT (they are data: content-equal pools are
        # interchangeable, and the service's per-namespace model cache
        # makes same-config engines share one instance, which is where
        # the compile-free second job comes from).
        key = None
        if aot_cache is not None:
            k = self._k
            key = (
                aot_cache, id(self._model), k.seeds_digest,
                self._T, self._L, self._D, self._K, self._cap,
                self._stride, self._A, self._P,
                self._cov_layout is not None,
            )
            fn = _WAVE_FN_CACHE.get(key)
            if fn is not None:
                return fn
        fn = jax.jit(jax.vmap(self._k._tenant_wave))
        if key is not None:
            _WAVE_FN_CACHE[key] = fn
            while len(_WAVE_FN_CACHE) > _WAVE_FN_CACHE_MAX:
                _WAVE_FN_CACHE.pop(next(iter(_WAVE_FN_CACHE)))
        return fn

    # -- wave dispatch ------------------------------------------------------

    def run_wave(self) -> None:
        """One stacked wave: every non-stopped tenant advances by
        ``wave_steps`` fused steps; one stats pull lands the per-tenant
        deltas and feeds the engine instruments plus the monitor's wave
        stream."""
        fault_point("swarm.wave")
        self._wave_calls += 1
        prev = self._stats_host
        warm = self.warmup_seconds is None
        t0 = time.perf_counter()
        with self._tracer.span(
            "swarm.wave", call=self._wave_calls, tenants=self._T,
            lanes=self._L, wave_steps=self._K,
        ) as sp, device_step_annotation("swarm.wave", self._wave_calls):
            self._carry = self._wave_fn(self._carry)
            stats = jax.device_get(self._carry["stats"])
            self._disc_found_host = np.asarray(self._carry["disc"]["found"])
            d_steps = int(stats["count"].sum() - prev["count"].sum())
            d_unique = int(
                stats["sample_unique"].sum() - prev["sample_unique"].sum()
            )
            live = int((~stats["stopped"]).sum()) * self._L
            sp.set(
                states=d_steps,
                generated=d_steps,
                new_unique=d_unique,
                live_lanes=live,
                max_depth=int(stats["max_depth"].max()),
            )
        if warm:
            self.warmup_seconds = time.perf_counter() - t0
        self._stats_host = stats
        self._m_waves.inc()
        self._m_steps.inc(d_steps)
        self._m_unique.inc(max(0, d_unique))
        for field, counter in (
            ("walks", self._m_walks),
            ("restarts", self._m_restarts),
            ("restart_dups", self._m_restart_dups),
            ("overflow", self._m_overflow),
        ):
            counter.inc(max(0, int(stats[field].sum() - prev[field].sum())))
        self._g_sat.set(int(stats["sample_sat"].any()))
        self._g_occ.set(
            float(stats["sample_unique"].max()) / float(self._cap)
        )

    # -- per-tenant host views ---------------------------------------------

    def tenant_stats(self, t: int) -> dict:
        """The slot's cumulative host-visible numbers (idempotent reads
        of the last pull — a missed absorb self-heals next wave)."""
        s = self._stats_host
        return {k: v[t].item() for k, v in s.items()}

    def tenant_found_names(self, t: int) -> List[str]:
        flags = self._disc_found_host[t]
        return [
            p.name for i, p in enumerate(self._properties) if flags[i]
        ]

    def tenant_discoveries_fps(self, t: int):
        """Pulls the slot's discovery trace buffers and materializes
        fp lists per discovered property (empty walks — a seed already
        out of boundary — settle the property with no path, matching
        the host simulation semantics)."""
        disc = jax.tree_util.tree_map(
            lambda x: np.asarray(x[t]), self._carry["disc"]
        )
        fps: Dict[str, List[int]] = {}
        empty = set()
        hi = disc["hi"].astype(np.uint64)
        lo = disc["lo"].astype(np.uint64)
        for i, p in enumerate(self._properties):
            if not disc["found"][i]:
                continue
            n = int(disc["len"][i])
            if n == 0:
                empty.add(p.name)
                continue
            fps[p.name] = (
                (hi[i, :n] << np.uint64(32)) | lo[i, :n]
            ).tolist()
        return fps, empty

    def export_slot_payload(self, t: int, seed: int, run_state: dict):
        """The slot as a checkpoint-v3 payload slice: standard header +
        the ``swarm`` extension carrying PRNG keys and walk buffers
        verbatim. Resuming (solo or into a later pack) continues the
        exact walk sequence — bit-identical to an uninterrupted run."""
        slot = self.read_slot(t)
        stats = {k: v.item() for k, v in slot["stats"].items()}
        payload = {
            **checkpoint_header("swarm", self._model, self._A, False),
            "version": 3,
            "state_count": int(stats["count"]),
            "unique_count": int(stats["sample_unique"]),
            "max_depth": int(stats["max_depth"]),
            "swarm": {
                "slot": slot,
                "seed": int(seed),
                "lanes": self._L,
                "max_trace_len": self._D,
                "sample_capacity": self._cap,
                "sample_stride": self._stride,
                "seeded": self._seeded,
                # Pool CONTENT, not just the flag: resuming into a
                # same-shape but different restart pool would silently
                # diverge the walk sequence.
                "seeds_digest": self._k.seeds_digest,
                **run_state,
            },
        }
        return payload

    def restore_slot_carry(self, payload: dict):
        """Validates a swarm payload against this engine's model and
        shapes and returns the tenant carry it froze."""
        validate_checkpoint_header(
            payload,
            "swarm",
            "exhaustive checkpoints carry a frontier queue, not walk "
            "buffers; use frontier_seeds_from_payload for the hybrid "
            "handoff instead",
            self._model,
            self._A,
            False,
        )
        sw = payload["swarm"]
        for knob, mine in (
            ("lanes", self._L),
            ("max_trace_len", self._D),
            ("sample_capacity", self._cap),
            ("sample_stride", self._stride),
            ("seeded", self._seeded),
            ("seeds_digest", self._k.seeds_digest),
        ):
            if sw.get(knob) != mine:
                raise ValueError(
                    f"swarm payload {knob}={sw.get(knob)!r} does not "
                    f"match this engine ({mine!r}); the walk sequence "
                    "would diverge from the original run"
                )
        # Coverage is a carry-SHAPE knob too (the cov vector is a slot
        # leaf): refuse a flag mismatch explicitly instead of failing
        # with an opaque pytree/KeyError inside write_slot.
        had_cov = "cov" in sw["slot"]
        want_cov = self._cov_layout is not None
        if had_cov != want_cov:
            raise ValueError(
                f"swarm payload coverage={had_cov} does not match this "
                f"engine (coverage={want_cov}); resume with the same "
                "coverage setting the run was spawned with"
            )
        return jax.tree_util.tree_map(jnp.asarray, sw["slot"])


class SwarmChecker(Checker):
    """The solo swarm run ``spawn_swarm`` returns: one engine slot, a
    worker thread driving waves until every property has a discovery or
    ``target_state_count`` is reached (reference simulation semantics),
    with preempt/resume and the full Checker surface."""

    supports_preempt = True
    # Honest capability surface (the PR 12 pattern): swarm jobs pack —
    # lane blocks over one shared dispatch (``SwarmPackedEngine``).
    supports_packing = True
    packing_reason = None

    def __init__(
        self,
        options,
        seed: int,
        lanes: int = 1024,
        wave_steps: int = 1024,
        max_trace_len: Optional[int] = None,
        sample_capacity: int = 1 << 15,
        sample_stride: int = 1,
        seeds=None,
        resume_from=None,
        coverage: bool = False,
        run_id=None,
        aot_cache: Optional[str] = None,
    ):
        model = options.model
        if not isinstance(model, BatchableModel):
            raise TypeError(
                f"spawn_swarm requires a BatchableModel; "
                f"{type(model).__name__} does not implement the packed "
                "protocol"
            )
        if options._symmetry is not None:
            raise NotImplementedError(
                "symmetry-aware cycle detection is host-only; use "
                "spawn_simulation for symmetric models"
            )
        if options._visitor is not None:
            raise NotImplementedError(
                "per-state visitors replay O(depth²) host paths; use "
                "spawn_simulation for visitor-driven runs"
            )
        self._model = model
        self._properties = model.properties()
        self.run_id = run_id
        self._registry = metrics_registry(run_id) if run_id else None
        self._tracer = get_tracer(run_id)
        self._seed = int(seed)
        self._depth_cap = options._target_max_depth
        self._target = options._target_state_count
        # Trace-buffer depth: an explicit ``max_trace_len``, else the
        # user's depth cap (capped walks are then a semantic bound),
        # else the default. The cap itself is a RUNTIME scalar in the
        # carry — one buffer shape serves every cap, which is what keeps
        # solo and service-packed runs bit-identical. Walks hitting the
        # buffer below the cap are TRUNCATED and counted
        # (``swarm.trace_overflow``).
        D = max_trace_len or (self._depth_cap or 512)

        cov_layout = None
        if coverage:
            from ..telemetry.coverage import DeviceCoverage

            cov_layout = DeviceCoverage(
                model.packed_action_count(), len(self._properties)
            )
        if isinstance(seeds, dict) and "chunks" in seeds:
            seeds = frontier_seeds_from_payload(model, seeds)
        self._engine = SwarmEngine(
            model,
            lanes=lanes,
            wave_steps=wave_steps,
            max_trace_len=D,
            sample_capacity=sample_capacity,
            sample_stride=sample_stride,
            max_tenants=1,
            seeds=seeds,
            coverage_layout=cov_layout,
            aot_cache=aot_cache,
            tracer=self._tracer,
            registry=self.metrics(),
        )
        if coverage:
            self._init_coverage(
                "swarm", True, model.packed_action_count()
            )
            self._cov_last = np.zeros(
                (cov_layout.size,), np.int64
            )
        if resume_from is not None:
            carry = self._engine.restore_slot_carry(resume_from)
            if coverage:
                # The restored carry's cov vector is CUMULATIVE over the
                # pre-preempt run, and the previous incarnation already
                # consumed it into this run_id's registry — baseline the
                # delta here or resume double-counts the whole prefix.
                self._cov_last = np.asarray(
                    carry["cov"], dtype=np.int64
                )
        else:
            carry = self._engine.fresh_tenant_carry(
                self._seed,
                depth_cap=self._depth_cap,
                target=self._target,
            )
        self._engine.write_slot(0, carry)

        self._state_count = 0
        self._max_depth = 0
        self._unique_sample = 0
        self._sample_saturated = False
        self._trace_overflows = 0
        self._discoveries_fps: Dict[str, List[int]] = {}
        self._empty_discoveries: set = set()
        self._found_names: List[str] = []
        self._preempt_event = threading.Event()
        self._done_event = threading.Event()
        self._error: Optional[BaseException] = None
        self._jit_fp_single = jax.jit(model.packed_fingerprint)

        self._handles = [
            threading.Thread(target=self._run, name="swarm", daemon=True)
        ]
        self._handles[0].start()

    @property
    def warmup_seconds(self):
        return self._engine.warmup_seconds

    # -- worker loop --------------------------------------------------------

    def _run(self):
        try:
            self._explore()
        except BaseException as e:  # noqa: BLE001 - via worker_error
            self._error = e
            self._abort_attribution()
        finally:
            self._finalize_coverage(set(self._discoveries_fps))
            self._done_event.set()

    def _absorb_stats(self):
        s = self._engine.tenant_stats(0)
        self._state_count = int(s["count"])
        self._max_depth = int(s["max_depth"])
        self._unique_sample = int(s["sample_unique"])
        self._sample_saturated = bool(s["sample_sat"])
        self._trace_overflows = int(s["overflow"])
        self._found_names = self._engine.tenant_found_names(0)
        if self._cov is not None:
            vec = np.asarray(
                self._engine._carry["cov"][0], dtype=np.int64
            )
            delta = vec - self._cov_last
            self._cov_last = vec
            self._cov.consume_device(
                delta, self._engine._cov_layout,
                first_attempt=True, max_depth=self._max_depth,
            )
            self._cov.emit_wave_span()
        return s

    def _explore(self):
        if not self._properties and self._target is None:
            return
        while True:
            self._engine.run_wave()
            s = self._absorb_stats()
            if self._preempt_event.is_set() and not s["stopped"]:
                self._preempt_payload = self._engine.export_slot_payload(
                    0, self._seed, {}
                )
                return
            if s["stopped"]:
                fps, empty = self._engine.tenant_discoveries_fps(0)
                self._discoveries_fps = fps
                self._empty_discoveries = empty
                for name, trail in fps.items():
                    self._engine._h_hit_depth.observe(len(trail))
                return

    # -- path reconstruction ------------------------------------------------

    def _host_fp(self, host_state) -> int:
        hi, lo = self._jit_fp_single(self._model.pack_state(host_state))
        return fp_to_int(hi, lo)

    _seed_fp_map = None

    def _replay(self, fps: List[int]) -> Path:
        if not self._engine._seeded:
            return Path.from_fingerprints(
                self._model, fps, fp_of=self._host_fp
            )
        # Seeded walks start mid-space: find the seed whose fingerprint
        # opens the trail and replay the fragment from there. The
        # fp -> seed-index map is one vmapped fingerprint pass, built on
        # first replay.
        if self._seed_fp_map is None:
            hi, lo = jax.jit(jax.vmap(self._engine._fp_fn))(
                self._engine._seeds
            )
            fps64 = (
                np.asarray(hi).astype(np.uint64) << np.uint64(32)
            ) | np.asarray(lo).astype(np.uint64)
            fp_map: Dict[int, int] = {}
            for i, f in enumerate(fps64.tolist()):
                fp_map.setdefault(int(f), i)
            self._seed_fp_map = fp_map
        idx = self._seed_fp_map.get(int(fps[0]))
        if idx is None:
            raise RuntimeError(
                "seeded discovery trail does not start at any seed "
                "state (the seed pool changed between run and replay?)"
            )
        packed = jax.tree_util.tree_map(
            lambda x: x[idx], self._engine._seed_host
        )
        state = self._model.unpack_state(packed)
        return _path_from_state(self._model, state, fps, self._host_fp)

    # -- Checker surface ----------------------------------------------------

    def model(self):
        return self._model

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        # The honest coverage estimate: distinct sampled walk
        # fingerprints — a LOWER bound once the sample table saturates
        # (``sample_saturated`` / ``coverage_estimate()``), never the
        # reference's total-count approximation.
        return self._unique_sample

    def coverage_estimate(self) -> dict:
        """The unique-coverage sample: distinct fingerprints observed,
        whether the fixed-capacity table saturated (the estimate is then
        a lower bound), and the raw walk-step total for context."""
        return {
            "unique_sample": self._unique_sample,
            "saturated": self._sample_saturated,
            "walk_steps": self._state_count,
            "sample_capacity": self._engine._cap,
        }

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: self._replay(fps)
            for name, fps in list(self._discoveries_fps.items())
        }

    def _discovery_names(self) -> List[str]:
        return list(self._found_names)

    def handles(self) -> List[threading.Thread]:
        handles, self._handles = self._handles, []
        return handles

    def is_done(self) -> bool:
        return self._done_event.is_set()

    def worker_error(self) -> Optional[BaseException]:
        return self._error

    def request_preempt(self) -> None:
        self._preempt_event.set()

    def state_digest(self) -> dict:
        digest = super().state_digest()
        digest["swarm"] = {
            "lanes": self._engine._L,
            "wave_steps": self._engine._K,
            "sample": self.coverage_estimate(),
            "trace_overflows": self._trace_overflows,
        }
        return digest


def _path_from_state(model, start_state, fps: List[int], fp_of) -> Path:
    """``Path.from_fingerprints`` from an arbitrary start state (the
    hybrid mode's seeded walks do not begin at an init state)."""
    if fp_of(start_state) != fps[0]:
        raise ValueError("start state does not match the trail head")
    output = []
    last_state = start_state
    for next_fp in fps[1:]:
        found = None
        for a, s in model.next_steps(last_state):
            if fp_of(s) == next_fp:
                found = (a, s)
                break
        if found is None:
            raise RuntimeError(
                f"seeded walk replay diverged at fingerprint {next_fp}"
            )
        output.append((last_state, found[0]))
        last_state = found[1]
    output.append((last_state, None))
    return Path(output)


class _TenantWalkView(Checker):
    """A packed swarm tenant's Checker-shaped view: cumulative counts,
    discovery names, and (once the tenant stops) full discovery paths —
    what the service's ``_finalize`` consumes."""

    supports_preempt = True
    supports_packing = True
    packing_reason = None

    def __init__(self, pack: "SwarmPackedEngine", key: str, slot: int,
                 run_id=None):
        self._pack = pack
        self._key = key
        self._slot = slot
        self._model = pack._engine._model
        self.run_id = run_id
        self._registry = metrics_registry(run_id) if run_id else None
        self._tracer = get_tracer(run_id)
        self._stats: dict = {}
        self._found: List[str] = []
        self._fps: Dict[str, List[int]] = {}
        self._stopped = False
        self._last = {}
        reg = self.metrics()
        self._m = {
            "count": reg.counter("swarm.walk_steps"),
            "walks": reg.counter("swarm.walks_completed"),
            "restarts": reg.counter("swarm.restarts"),
            "restart_dups": reg.counter("swarm.restarts_deduped"),
            "overflow": reg.counter("swarm.trace_overflow"),
            "sample_unique": reg.counter("swarm.unique_sample"),
        }

    @property
    def warmup_seconds(self):
        return self._pack._engine.warmup_seconds

    def _prime(self, stats: dict, found_names: List[str]) -> None:
        """Admission-time baseline: a RESUMED slot's cumulative totals
        were already recorded into this run's registry by the previous
        incarnation — seed ``_last`` so only post-admission deltas
        count (a fresh slot's zeros make this a no-op)."""
        self._stats = stats
        self._found = found_names
        self._stopped = bool(stats.get("stopped"))
        for field in self._m:
            self._last[field] = int(stats.get(field, 0))

    def _absorb(self, stats: dict, found_names: List[str]) -> None:
        self._stats = stats
        self._found = found_names
        self._stopped = bool(stats.get("stopped"))
        for field, counter in self._m.items():
            cur = int(stats.get(field, 0))
            prev = self._last.get(field, 0)
            if cur > prev:
                counter.inc(cur - prev)
                self._last[field] = cur

    def _finish(self, fps: Dict[str, List[int]]) -> None:
        self._fps = fps
        self._stopped = True

    @property
    def _trace_overflows(self) -> int:
        return int(self._stats.get("overflow", 0))

    def model(self):
        return self._model

    def state_count(self) -> int:
        return int(self._stats.get("count", 0))

    def unique_state_count(self) -> int:
        return int(self._stats.get("sample_unique", 0))

    def coverage_estimate(self) -> dict:
        return {
            "unique_sample": self.unique_state_count(),
            "saturated": bool(self._stats.get("sample_sat", False)),
            "walk_steps": self.state_count(),
            "sample_capacity": self._pack._engine._cap,
        }

    def max_depth(self) -> int:
        return int(self._stats.get("max_depth", 0))

    def _host_fp(self, host_state) -> int:
        hi, lo = self._pack._jit_fp_single(
            self._model.pack_state(host_state)
        )
        return fp_to_int(hi, lo)

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: Path.from_fingerprints(
                self._model, fps, fp_of=self._host_fp
            )
            for name, fps in list(self._fps.items())
        }

    def _discovery_names(self) -> List[str]:
        return list(self._found)

    def handles(self) -> List[threading.Thread]:
        return []

    def is_done(self) -> bool:
        return self._stopped

    def worker_error(self) -> Optional[BaseException]:
        return None

    def state_digest(self) -> dict:
        digest = super().state_digest()
        digest["swarm"] = {"packed": True, **self.coverage_estimate()}
        return digest


class SwarmPackedEngine:
    """The service packer's swarm engine: up to ``max_tenants`` swarm
    jobs co-schedule onto ONE stacked wave dispatch. Implements the
    ``TenantPackedEngine`` protocol (admit / step / drop / release /
    free_slots / live_count / faulted_keys / fault_error / close) so
    ``CheckService._run_packed_slice`` drives it unchanged. Walk fleets
    are lane-independent, so per-tenant verdicts are bit-identical to
    solo runs by construction — no salting required."""

    def __init__(
        self,
        model,
        *,
        lanes: int = 1024,
        wave_steps: int = 1024,
        max_trace_len: int = 256,
        sample_capacity: int = 1 << 15,
        sample_stride: int = 1,
        max_tenants: int = 8,
        aot_cache: Optional[str] = None,
    ):
        self._engine = SwarmEngine(
            model,
            lanes=lanes,
            wave_steps=wave_steps,
            max_trace_len=max_trace_len,
            sample_capacity=sample_capacity,
            sample_stride=sample_stride,
            max_tenants=max_tenants,
            aot_cache=aot_cache,
        )
        self._jit_fp_single = jax.jit(model.packed_fingerprint)
        self._slots: List[Optional[str]] = [None] * self._engine._T
        self._views: Dict[str, _TenantWalkView] = {}
        self._seeds: Dict[str, int] = {}
        self._reported: set = set()
        self._faulted: Dict[str, BaseException] = {}

    # -- the TenantPackedEngine protocol ------------------------------------

    def free_slots(self) -> int:
        return sum(1 for s in self._slots if s is None)

    def live_count(self) -> int:
        # A stopped-but-not-yet-REPORTED tenant still counts live: its
        # completion may have been rolled back by a same-wave peer
        # fault, and the service's drive loop gates on this count — an
        # early zero would strand the finished job in JOB_RUNNING.
        return sum(
            1
            for jid in self._slots
            if jid is not None
            and not (
                self._views[jid]._stopped and jid in self._reported
            )
        )

    def faulted_keys(self):
        return list(self._faulted)

    def fault_error(self, key: str):
        return self._faulted.get(key)

    def admit(self, job_id: str, run_id=None, *, seed: int = 0,
              depth_cap=None, target_state_count=None,
              resume_from=None) -> _TenantWalkView:
        """Claims a lane-block slot: fresh walks from ``seed``, or a
        suspended job's exact carry (``resume_from`` = the standard
        swarm payload — resumes from a solo run or an earlier pack
        bit-identically)."""
        try:
            slot = self._slots.index(None)
        except ValueError:
            raise RuntimeError("no free swarm lane slots") from None
        if resume_from is not None:
            carry = self._engine.restore_slot_carry(resume_from)
            seed = int(resume_from["swarm"].get("seed", seed))
        else:
            carry = self._engine.fresh_tenant_carry(
                seed, depth_cap=depth_cap, target=target_state_count
            )
        self._engine.write_slot(slot, carry)
        self._slots[slot] = job_id
        self._seeds[job_id] = int(seed)
        view = _TenantWalkView(self, job_id, slot, run_id=run_id)
        view._prime(
            self._engine.tenant_stats(slot),
            self._engine.tenant_found_names(slot),
        )
        self._views[job_id] = view
        self._reported.discard(job_id)
        self._faulted.pop(job_id, None)
        return view

    def step(self) -> List[str]:
        """One shared wave for every live tenant; returns the job ids
        that finished this wave (stopped, discoveries materialized).
        A per-tenant harvest fault raises ``TenantFaultError`` so the
        service drops ONLY that tenant (its slot carry is intact — the
        payload slice resumes it from this very wave boundary) while
        survivors keep walking."""
        self._engine.run_wave()
        done: List[str] = []
        try:
            for slot, jid in enumerate(self._slots):
                if jid is None or jid in self._faulted:
                    continue
                view = self._views[jid]
                try:
                    fault_point("swarm.tenant.verdict", tenant=jid)
                    stats = self._engine.tenant_stats(slot)
                    view._absorb(
                        stats, self._engine.tenant_found_names(slot)
                    )
                    if stats["stopped"] and jid not in self._reported:
                        fps, _empty = (
                            self._engine.tenant_discoveries_fps(slot)
                        )
                        view._finish(fps)
                        self._reported.add(jid)
                        done.append(jid)
                except Exception as e:  # noqa: BLE001 - blast radius
                    self._faulted[jid] = e
                    raise TenantFaultError(jid, e) from e
        except BaseException:
            # The raised fault discards this wave's ``done`` list, so
            # the completions it carried must become re-reportable —
            # a finished tenant left in _reported but never RETURNED
            # would sit in JOB_RUNNING forever (the finish harvest is
            # idempotent, so the next step() re-reports it exactly).
            for jid in done:
                self._reported.discard(jid)
            raise
        return done

    def drop(self, job_id: str, discard: bool = False):
        """Releases the tenant's slot; unless ``discard``, hands back
        its payload slice (resumable solo or into a later pack)."""
        slot = self._slots.index(job_id)
        payload = None
        if not discard:
            payload = self._engine.export_slot_payload(
                slot, self._seeds.get(job_id, 0), {}
            )
        self._engine.clear_slot(slot)
        self._slots[slot] = None
        self._views.pop(job_id, None)
        self._seeds.pop(job_id, None)
        self._faulted.pop(job_id, None)
        self._reported.discard(job_id)
        return payload

    def release(self, job_id: str) -> None:
        """Frees a COMPLETED tenant's slot (the service calls this
        after harvesting the verdict) — exactly a discard-drop, shared
        so the slot/view/seed bookkeeping lives in one place."""
        self.drop(job_id, discard=True)

    def close(self) -> None:
        """Nothing persistent to tear down — the engine is carry +
        executables, both process-cached."""
