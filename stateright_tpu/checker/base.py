"""The post-spawn checker handle: counts, discoveries, joins, assertions.

Reference: ``Checker`` trait at ``/root/reference/src/checker.rs:273-557``.
This is the compatibility surface that tests hit; every backend (host BFS/DFS,
on-demand, simulation, TPU) returns an object with this interface.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Generic, List, Optional, TypeVar

from ..core.model import Expectation
from ..core.path import Path
from ..report import ReportData, ReportDiscovery, Reporter

State = TypeVar("State")
Action = TypeVar("Action")

EXAMPLE = "example"
COUNTEREXAMPLE = "counterexample"

# Reusable no-op context for attribution-off hot paths: nullcontext holds
# no state, so one instance serves every call site (the attribution-off
# overhead budget test prices exactly this object's enter/exit).
_NULL_CTX = contextlib.nullcontext()


class Checker(Generic[State, Action]):
    """Base class for checker handles. Subclasses implement the abstract
    accessors; joins/reports/assertions are shared."""

    # Wave-timeline attribution engine (telemetry/attribution.py): the
    # device checkers set it via _init_attribution; host engines have no
    # device/host boundary to attribute and leave the class default.
    _attr = None

    # Coverage ledger (telemetry/coverage.py): opt-in on the device
    # checkers (coverage=True — the reductions ride the wave jits),
    # always-on for the host engines (their per-state Python loop dwarfs
    # the per-block dict merges).
    _cov = None
    _cov_layout = None
    _cov_antecedents = None

    # -- abstract surface --------------------------------------------------

    def model(self):
        raise NotImplementedError

    def state_count(self) -> int:
        """Total states generated including repeats (>= unique_state_count)."""
        raise NotImplementedError

    def unique_state_count(self) -> int:
        raise NotImplementedError

    def max_depth(self) -> int:
        raise NotImplementedError

    def discoveries(self) -> Dict[str, Path]:
        """Map from property name to discovery path."""
        raise NotImplementedError

    def handles(self) -> List[threading.Thread]:
        """Extract (and clear) the worker thread handles."""
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    def check_fingerprint(self, fp: int) -> None:
        """Ask the checker to check the given fingerprint (on-demand only)."""

    def run_to_completion(self) -> None:
        """Ask the checker to run to completion (on-demand only)."""

    def worker_error(self) -> Optional[BaseException]:
        """The first exception raised by a worker thread, if any."""
        return None

    # -- preemption (device checkers implement; see checker/tpu.py) --------

    _preempt_payload = None

    # Honest preemptibility surface (checking-as-a-service): True on the
    # backends whose request_preempt() actually yields a resumable
    # payload. The service exposes it per job so operators can SEE which
    # jobs serialize the device instead of discovering it from a
    # NotImplementedError at slice time.
    supports_preempt = False

    # Honest packability surface (same convention): True on backends
    # whose runs can share one physical dispatch with other tenants
    # (tenant-packed BFS waves, swarm lane blocks); ``packing_reason``
    # is the human-readable downgrade reason on the backends that
    # cannot. This is the backend's STATIC self-declaration; the
    # per-job ``packable``/``packable_reason`` fields in job status() /
    # HTTP / service_report come from the service's admission
    # classifiers, which also account for service-level knobs (packing
    # disabled, spawn overrides, no AOT namespace).
    supports_packing = False
    packing_reason: Optional[str] = None

    # Walk-truncation honesty (simulation backends): the number of walks
    # aborted because their trace buffer overflowed (NOT a semantic
    # depth cap). Nonzero means absence of discoveries on those walks is
    # truncation, not evidence — the report loop warns once at run end.
    _trace_overflows = 0

    def request_preempt(self) -> None:
        """Asks the worker to suspend at the next wave boundary and
        drain its state into an in-memory checkpoint payload. Device
        checkers implement this (the service's scheduler uses it); the
        host engines' per-state loops have no payload format to yield."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support preemption"
        )

    @property
    def preempted(self) -> bool:
        """True when the worker suspended in response to a preempt
        request (the run is incomplete and resumable)."""
        return self._preempt_payload is not None

    def preempt_payload(self):
        """The suspended run's in-memory checkpoint payload, or None
        (not preempted / finished first). Pass as ``resume_from=``."""
        return self._preempt_payload

    # Run identity: checkers spawned with ``run_id=`` record into their
    # own metrics registry and stamp their trace spans, so concurrent
    # runs in one process never collide (the service's per-job scoping).
    run_id = None
    _registry = None

    def metrics(self):
        """The telemetry metrics registry this checker records into:
        the process-local default, or — when the checker was spawned
        with ``run_id=`` — that run's own registry (see
        ``stateright_tpu.telemetry.metrics_registry``).
        ``metrics().snapshot()`` is the cheap point-in-time view
        reporters and benches consume."""
        if self._registry is not None:
            return self._registry
        from ..telemetry import metrics_registry

        return metrics_registry()

    # -- wave-timeline attribution (shared by the device checkers) ---------

    def _init_attribution(self, prefix: str, attribution) -> None:
        """Installs the attribution engine when requested: ``True``
        builds a ``WaveAttribution`` recording into ``self._tracer``, or
        pass a pre-built engine (injectable clock — the deterministic
        classifier tests drive a fake one). Falsy leaves attribution
        off (the class default)."""
        if not attribution:
            return
        from ..telemetry.attribution import WaveAttribution

        self._attr = (
            attribution
            if isinstance(attribution, WaveAttribution)
            else WaveAttribution(
                prefix, tracer=self._tracer, registry=self.metrics()
            )
        )

    def _phase(self, name: str):
        """An attribution phase window, or the shared no-op context when
        attribution is off (the off path must stay free — budget-tested)."""
        if self._attr is None:
            return _NULL_CTX
        return self._attr.phase(name)

    def _wave_window(self, kind: str = "wave"):
        """One attributed wave/drain window (no-op when attribution off)."""
        if self._attr is None:
            return _NULL_CTX
        return self._attr.wave(kind)

    def _phase_overlapped(self, name: str):
        """An attribution window for host-tier work running on the async
        pipeline's worker thread, shadowed under device compute: records
        into the thread-safe ``overlapped`` ledger instead of the wave
        window (``telemetry/attribution.py``). No-op when attribution is
        off."""
        if self._attr is None:
            return _NULL_CTX
        return self._attr.overlapped(name)

    # -- async pipeline plumbing (device checkers set _pipe; see
    # checker/pipeline.py) --------------------------------------------------

    _pipe = None

    def _shutdown_pipeline(self) -> None:
        """Run-end epoch barrier + worker teardown: a verdict error that
        nothing drained yet becomes the worker error, and the host
        thread never outlives the run."""
        if self._pipe is None:
            return
        try:
            self._pipe.drain()
        except BaseException as e:  # noqa: BLE001 - surfaced via worker_error
            if self._error is None:
                self._error = e
        finally:
            self._pipe.close()

    def _checkpoint_write(self, path, payload) -> None:
        """Pipeline-worker half of a deferred checkpoint (the payload
        was snapshotted at the epoch barrier; only the pickle + atomic
        rename ride the worker)."""
        from .tpu import atomic_pickle

        with self._phase_overlapped("checkpoint"):
            atomic_pickle(path, payload)

    def _abort_attribution(self) -> None:
        """Worker-error-path cleanup: closes any window the crash left
        open so the dying wave's ``.pipeline`` span still reaches the
        sinks and no dangling state survives into a ledger read. Never
        raises — it must not mask the real error."""
        if self._attr is None:
            return
        try:
            self._attr.abort()
        except Exception:  # noqa: BLE001 - never mask the worker error
            pass

    @property
    def attribution(self):
        """The ``WaveAttribution`` engine, or None outside attribution
        mode."""
        return self._attr

    def attribution_report(self):
        """The wave-timeline phase ledger
        (``stateright_tpu.telemetry.attribution``): where real-run
        wall-clock went between device work. None unless the backend
        supports attribution mode and was spawned with
        ``attribution=True`` (the device checkers are; host engines have
        no device/host boundary to attribute)."""
        return self._attr.report() if self._attr is not None else None

    # -- coverage ledger (device checkers opt in; host engines always-on) ---

    def _init_coverage(self, prefix: str, coverage, action_count: int,
                       symmetry: bool = False) -> None:
        """Installs the coverage ledger + device reduction layout when
        requested. Falsy leaves coverage off (the class default) and the
        wave jits trace exactly as before — the off-mode cost is zero."""
        if not coverage:
            return
        from ..telemetry.coverage import (
            CoverageLedger,
            DeviceCoverage,
            coverage_action_labels,
        )

        model = self._model
        props = self._properties
        self._cov = CoverageLedger(
            prefix,
            props,
            action_labels=coverage_action_labels(model, action_count),
            symmetry=symmetry,
            tracer=self._tracer,
            registry=self.metrics(),
        )
        self._cov_layout = DeviceCoverage(
            action_count, len(props), symmetry=symmetry
        )
        try:
            ants = list(model.packed_antecedents())
        except Exception:  # noqa: BLE001 - optional hook
            ants = [None] * len(props)
        if len(ants) != len(props):
            raise ValueError(
                "packed_antecedents() must align 1:1 with properties(): "
                f"{len(ants)} != {len(props)}"
            )
        self._cov_antecedents = ants

    def _finalize_coverage(self, discovered) -> None:
        """Run-end ledger finalize (summary instant + vacuity verdict);
        never raises — it must not mask a real worker error."""
        if self._cov is None:
            return
        try:
            self._cov.finalize(discovered=discovered)
        except Exception:  # noqa: BLE001
            pass

    @property
    def coverage(self):
        """The ``CoverageLedger``, or None when coverage is off."""
        return self._cov

    def coverage_report(self) -> Optional[dict]:
        """The state-space cartography (``telemetry/coverage.py``):
        per-action fire/fresh counts with dead-action detection,
        per-property exercise counts (vacuity), and shape statistics.
        None unless the backend records coverage (device checkers need
        ``coverage=True``; host engines are always-on)."""
        return self._cov.report() if self._cov is not None else None

    def serve_monitor(self, port: int = 0, **kwargs):
        """Starts the live in-process monitor HTTP server for this run
        (``stateright_tpu.telemetry.server.MonitorServer``): ``/metrics``
        (Prometheus), ``/status`` (JSON progress + ETA), ``/events``
        (SSE wave/storage stream). ``port=0`` binds an ephemeral port
        (``monitor.port`` / ``monitor.url``); pass ``stall_deadline_s=``
        to arm the watchdog and ``flight_recorder=True`` for crash
        dumps. A checker spawned with ``run_id=`` serves ITS registry
        and only its own wave stream (``run_filter``), so a multi-job
        process can serve one monitor per job. Returns the server; call
        ``monitor.close()`` when done."""
        from ..telemetry.server import MonitorServer

        kwargs.setdefault("registry", self.metrics())
        if self.run_id is not None:
            kwargs.setdefault("run_id", self.run_id)
            kwargs.setdefault("run_filter", self.run_id)
        return MonitorServer(checker=self, port=port, **kwargs)

    def state_digest(self) -> dict:
        """A cheap, never-raising summary of where the run stands — the
        flight recorder's crash payload and the stall watchdog's context.
        Backends extend it (device checkers add table capacity, storage
        tier stats, checkpoint path); every field is individually guarded
        because the digest is read mid-crash from arbitrary threads."""
        digest: dict = {"backend": type(self).__name__}
        for field, fn in (
            ("done", self.is_done),
            ("state_count", self.state_count),
            ("unique_state_count", self.unique_state_count),
            ("max_depth", self.max_depth),
        ):
            try:
                digest[field] = fn()
            except Exception:  # noqa: BLE001 - mid-crash best effort
                digest[field] = None
        try:
            digest["discoveries"] = sorted(self._discovery_names())
        except Exception:  # noqa: BLE001
            digest["discoveries"] = None
        return digest

    def _discovery_names(self) -> List[str]:
        """Discovery property names WITHOUT path reconstruction — the
        digest must stay cheap and safe mid-run; backends holding a
        fingerprint map override this."""
        return list(self.discoveries())

    # -- liveness surfaces (device mode + the host post-pass) ----------------

    # Honest capability surface (the PR 12 pattern): True on backends
    # whose ``liveness="device"`` spawn knob yields sound ``eventually``
    # verdicts via the device edge store. The service exposes it per job
    # so a downgrade to host-pass or default semantics is visible, not
    # discovered from a TypeError at spawn.
    supports_device_liveness = False
    _live = None
    _live_enabled = False
    _live_store = None
    _live_ins = None

    @property
    def liveness_mode(self) -> str:
        """How this run's ``eventually`` verdicts were produced:
        ``"device"`` (edge-store trim/reach — sound by construction),
        ``"host_pass"`` (the opt-in O(region) post-pass), or
        ``"default"`` (reference parity: the documented DAG-join/cycle
        false negatives)."""
        if getattr(self, "_live", None) == "device":
            return "device"
        if getattr(self, "_complete_liveness", False):
            return "host_pass"
        return "default"

    def liveness_report(self) -> dict:
        """The per-property liveness evidence the service surfaces:
        mode, device verdicts/outcomes, host-pass inconclusive names,
        edge-store stats, and whether a crashed run skipped the pass."""
        out: dict = {"mode": self.liveness_mode}
        outcomes = getattr(self, "_live_outcomes", None)
        if outcomes:
            out["outcomes"] = dict(outcomes)
        store = getattr(self, "_live_store", None)
        if store is not None:
            out["edge_store"] = store.stats()
        inconclusive = getattr(self, "_lasso_inconclusive", None)
        if inconclusive:
            out["inconclusive"] = sorted(inconclusive)
        if getattr(self, "_liveness_skipped_crashed", False):
            out["skipped_crashed_run"] = True
        return out

    def _with_device_liveness(self, out: Dict[str, Path]):
        """Merges device-liveness counterexamples into ``out`` without
        overriding default-semantics discoveries, and signals (once)
        when a crashed run makes the missing verdicts untrustworthy —
        a missing counterexample must never read as absence."""
        if not getattr(self, "_live_enabled", False):
            return out
        for name, path in getattr(self, "_live_paths", {}).items():
            out.setdefault(name, path)
        if self.is_done() and self.worker_error() is not None:
            self._signal_liveness_skip()
        return out

    def _flush_live_edges(self) -> None:
        """Pre-analysis hook: backends with a device-resident edge
        store drain it here; backends that absorb per wave need
        nothing."""

    def _run_liveness_analysis(self, prefix: str) -> None:
        """End-of-exploration device-liveness pass, shared by the
        device checkers (worker thread, so ``is_done()`` implies the
        verdicts exist and a crash surfaces via ``worker_error``).
        Preempted runs skip it — the edge store rides the checkpoint
        payload and the resumed incarnation finishes the job."""
        if not self._live_enabled or self._preempt_payload is not None:
            return
        if self._pipe is not None:
            # Deferred edge absorbs must land before the store is read.
            self._pipe.drain()
        self._flush_live_edges()
        import time as _time

        from .device_liveness import analyze_liveness

        t0 = _time.perf_counter()
        with self._tracer.span(f"{prefix}.liveness.analysis"):
            self._live_paths, self._live_outcomes = analyze_liveness(
                self._model,
                self._properties,
                self._ebit,
                self._live_store,
                self._host_fp,
                set(self._discoveries_fp),
                instruments=self._live_ins,
                tracer=self._tracer,
            )
        self._live_ins.analysis_seconds.set(_time.perf_counter() - t0)
        # The PR 8 ledger surface coverage_report.py renders (edge-store
        # occupancy next to the met-bit population).
        self._tracer.instant(
            f"{prefix}.liveness.summary",
            store=self._live_store.stats(),
            outcomes=self._live_outcomes,
            analysis_s=_time.perf_counter() - t0,
        )

    def _signal_liveness_skip(self) -> None:
        """Crashed-run skip evidence: the ``liveness.skipped_crashed_run``
        counter plus a flag the reporter turns into a warning line."""
        if getattr(self, "_liveness_skipped_crashed", False):
            return
        self._liveness_skipped_crashed = True
        try:
            self.metrics().counter("liveness.skipped_crashed_run").inc()
        except Exception:  # noqa: BLE001 - signal, never a new failure
            pass

    # -- complete-liveness plumbing (shared by every spawning checker) ------

    def _setup_lasso(self, options) -> None:
        """Initializes the opt-in lasso-pass state (see checker/liveness.py)
        from the builder options. Refuses capped runs up front: the lasso
        search explores the whole condition-false region regardless of
        ``target_state_count``/``target_max_depth``, so on a model whose
        space is finite only because of the caps it would never terminate,
        and even when it did, its certificates could exceed the caps."""
        self._complete_liveness: bool = options._complete_liveness
        if self._complete_liveness and (
            options._target_state_count is not None
            or options._target_max_depth is not None
        ):
            raise ValueError(
                "complete_liveness() requires an uncapped run: the lasso "
                "search ignores target_state_count/target_max_depth and "
                "would search the full condition-false region"
            )
        self._lassos: Optional[Dict[str, Path]] = None
        self._lasso_lock = threading.Lock()
        # Bounded-pass knobs (builder) + the honest third outcome the
        # bounded pass fills (see checker/liveness.py).
        self._lasso_budget_states = getattr(
            options, "_liveness_budget_states", None
        )
        self._lasso_deadline_s = getattr(
            options, "_liveness_deadline_s", None
        )
        self._lasso_inconclusive: List[str] = []

    def _with_lassos(self, out: Dict[str, Path], done: bool, have):
        """Merges lasso counterexamples into ``out`` WITHOUT overriding
        existing entries — a terminal-state discovery recorded after the
        pass was cached must keep precedence."""
        from .liveness import checker_lasso_pass

        for name, path in checker_lasso_pass(self, done, have).items():
            out.setdefault(name, path)
        return out

    # -- shared behavior ---------------------------------------------------

    def join(self) -> "Checker":
        for h in self.handles():
            h.join()
        err = self.worker_error()
        if err is not None:
            raise RuntimeError("checker worker thread failed") from err
        return self

    def join_and_report(self, reporter: Reporter) -> "Checker":
        return self._report_loop(reporter, join=True)

    def report(self, reporter: Reporter) -> "Checker":
        return self._report_loop(reporter, join=False)

    def _report_loop(self, reporter: Reporter, join: bool) -> "Checker":
        start = time.monotonic()
        handles = self.handles() if join else []
        stop = threading.Event()

        def poll():
            while not self.is_done() and not stop.is_set():
                reporter.report_checking(
                    ReportData(
                        total_states=self.state_count(),
                        unique_states=self.unique_state_count(),
                        max_depth=self.max_depth(),
                        duration_secs=time.monotonic() - start,
                        done=False,
                    )
                )
                stop.wait(reporter.delay())

        if join:
            poller = threading.Thread(target=poll, daemon=True)
            poller.start()
            for h in handles:
                h.join()
            stop.set()
            poller.join()
        else:
            poll()
        err = self.worker_error()
        if err is not None:
            # Crashed run with a liveness pass armed: the pass was
            # skipped, so absence of a counterexample proves nothing —
            # say so before surfacing the crash (satellite of the
            # device-liveness PR; never silent).
            if getattr(self, "_complete_liveness", False) or getattr(
                self, "_live_enabled", False
            ):
                self._signal_liveness_skip()
                reporter.report_liveness(skipped_crashed=True)
            raise RuntimeError("checker worker thread failed") from err

        reporter.report_checking(
            ReportData(
                total_states=self.state_count(),
                unique_states=self.unique_state_count(),
                max_depth=self.max_depth(),
                duration_secs=time.monotonic() - start,
                done=True,
            )
        )
        discoveries = {
            name: ReportDiscovery(path, self.discovery_classification(name))
            for name, path in self.discoveries().items()
        }
        reporter.report_discoveries(discoveries)
        # Silent-adjustment honesty: configuration the checker rounded or
        # rewrote on the user's behalf (e.g. tile-aligned table capacity
        # for the tile-sweep kernels) is reported on every run — even an
        # early exit ran with the adjusted values.
        notes = getattr(self, "config_notes", None)
        if notes:
            reporter.report_config_notes(notes)
        # Run-end vacuity visibility (upstream-parity, see MIGRATING.md):
        # a sometimes/eventually property with no discovery is a silent
        # pass unless the reporter says so — even without the coverage
        # ledger. Only once checking actually completed: an early-exit
        # run proves nothing about undiscoverability.
        if self.is_done():
            undiscovered = [
                p
                for p in self.model().properties()
                if p.name not in discoveries
                and p.expectation in (
                    Expectation.SOMETIMES, Expectation.EVENTUALLY
                )
            ]
            if undiscovered:
                reporter.report_undiscovered(undiscovered)
            # Truncated-walk honesty (simulation backends): silently
            # aborted trace-buffer overflows must never read as
            # absence of discoveries.
            overflows = getattr(self, "_trace_overflows", 0)
            if overflows:
                reporter.report_truncation(overflows)
            # Bounded host-pass honesty: the discoveries() call above
            # already ran (and cached) the lasso pass, so the
            # inconclusive set is final here.
            inconclusive = getattr(self, "_lasso_inconclusive", None)
            if inconclusive:
                reporter.report_liveness(inconclusive=inconclusive)
        return self

    def discovery(self, name: str) -> Optional[Path]:
        return self.discoveries().get(name)

    def discovery_classification(self, name: str) -> str:
        prop = self.model().property(name)
        if prop.expectation in (Expectation.ALWAYS, Expectation.EVENTUALLY):
            return COUNTEREXAMPLE
        return EXAMPLE

    def assert_properties(self) -> None:
        """Verifies examples exist for all `sometimes` properties and no
        counterexamples exist for any `always`/`eventually` properties."""
        for p in self.model().properties():
            if p.expectation == Expectation.SOMETIMES:
                self.assert_any_discovery(p.name)
            else:
                self.assert_no_discovery(p.name)

    def assert_any_discovery(self, name: str) -> Path:
        found = self.discovery(name)
        if found is not None:
            return found
        if not self.is_done():
            raise AssertionError(
                f'Discovery for "{name}" not found, but model checking is incomplete.'
            )
        raise AssertionError(f'Discovery for "{name}" not found.')

    def assert_no_discovery(self, name: str) -> None:
        found = self.discovery(name)
        if found is not None:
            raise AssertionError(
                f'Unexpected "{name}" {self.discovery_classification(name)} '
                f"{found}Last state: {found.last_state()!r}\n"
            )
        if not self.is_done():
            raise AssertionError(
                f'Discovery for "{name}" not found, but model checking is incomplete.'
            )

    def assert_discovery(self, name: str, actions: List[Action]) -> None:
        """Verifies the specified actions constitute a valid discovery for the
        named property (by replaying them through the model), and that some
        discovery was in fact found."""
        additional_info: List[str] = []
        found = self.assert_any_discovery(name)
        model = self.model()
        for init_state in model.init_states():
            path = Path.from_actions(model, init_state, actions)
            if path is None:
                continue
            prop = model.property(name)
            if prop.expectation == Expectation.ALWAYS:
                if not prop.condition(model, path.last_state()):
                    return
            elif prop.expectation == Expectation.EVENTUALLY:
                states = path.into_states()
                is_liveness_satisfied = any(
                    prop.condition(model, s) for s in states
                )
                last_actions: List[Action] = []
                model.actions(states[-1], last_actions)
                is_path_terminal = not last_actions
                if not is_liveness_satisfied and is_path_terminal:
                    return
                if is_liveness_satisfied:
                    additional_info.append(
                        "incorrect counterexample satisfies eventually property"
                    )
                if not is_path_terminal:
                    additional_info.append("incorrect counterexample is nonterminal")
            else:  # SOMETIMES
                if prop.condition(model, path.last_state()):
                    return
        extra = f" ({'; '.join(additional_info)})" if additional_info else ""
        raise AssertionError(
            f'Invalid discovery for "{name}"{extra}, but a valid one was found. '
            f"found={found.into_actions()!r}"
        )
