"""Tenant-packed waves: many jobs advanced by ONE device dispatch.

``CheckService`` time-slicing (PR 10) pays a full checkpoint-v2 drain +
restore per slice — BENCH_r10 shows four concurrent 2pc-5 jobs burning
~2/3 of the device on that churn. This engine makes concurrency ~free
instead: up to ``max_tenants`` same-shape jobs share one physical wave.
Each dispatch compacts the live lanes of every resident tenant into one
dense frontier (a per-lane ``tid`` tenant-slot vector rides through
expand/fingerprint/property eval), dedups against ONE shared visited
table under **tenant-salted fingerprints**, and reduces results
(generated/fresh/depth/discoveries) per tenant by segmenting on the
lane's tenant id. Preempting a tenant is "drop its lanes" — its pending
frontier, counters, parent log, and storage partition hand back as a
standard checkpoint-v2 payload, with no device drain — and admission is
"claim a free lane slot" (optionally restoring such a payload, so a
dropped tenant resumes into a LATER pack or into a solo checker
unchanged).

Why each tenant's results are bit-identical to its solo run
-----------------------------------------------------------

Two properties carry the whole argument:

1. **XOR salting preserves within-tenant dedup exactly.** A tenant's
   table key is ``fp ^ salt`` — a bijection — so two of its states
   collide salted iff they collide raw; cross-tenant keys differ by an
   avalanche-mixed 64-bit constant (``ops/fingerprint.tenant_salt_pair``).
   Frontier rows, parent logs, discoveries, payloads, and the host-tier
   partitions always carry the ORIGINAL fingerprints.
2. **The owner-ticket scatter insert preserves lane order.** Packing
   uses ``hashset_insert_salted`` (the duplicate-tolerant unsorted
   insert): fresh lanes compact in natural lane order, and each tenant's
   lanes are assembled in its own FIFO frontier order — so a tenant's
   claim sequence is candidate-order-equivalent to its solo run under
   ``wave_dedup="scatter"`` (the CPU backend default). Re-chunking a
   FIFO frontier across different wave widths never changes claims:
   the first claimant of a key in per-tenant candidate order wins in
   every grouping (the same argument the bucket ladder's
   width-independence rests on). Hence counts, depths, parent pointers,
   ebit propagation, discovery fingerprints, and golden reports all
   match the solo run. (Early-exit runs — every property discovered —
   may overshoot by a different amount, exactly as the reference
   overshoots by up to a block.)

Out-of-core packing partitions the host tiers per tenant
(``storage.TenantPartitions``): the shared table's salted keys cannot be
attributed after the fact, but the engine knows each tenant's L0 claims
exactly (they are its parent-log stream), so an eviction drains each
tenant's since-last-eviction claims into its own run set and the wave's
two-phase probe runs per tenant partition. With ``async_pipeline=True``
those probes, the parent-log appends, and survivor re-entry ride one
FIFO ``HostPipeline`` worker behind the same merge fence the solo async
engine uses, overlapping with the next packed dispatch.

Device-transfer note: lane blocks live host-side (numpy) between waves,
so each wave pays one host->device frontier upload and one fresh-lane
download. On the CPU backend these are memcpys; a device-resident
per-tenant ring is the follow-up once this architecture lands on real
HBM.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.batch import BatchableModel
from ..core.model import Expectation
from ..core.path import Path
from ..native import make_fingerprint_store
from ..ops.fingerprint import fp64_pairs, fp_to_int, tenant_salt_pair
from ..ops.hashset import hashset_insert_salted, hashset_new
from ..telemetry import (
    TenantInstruments,
    WaveInstruments,
    get_tracer,
    metrics_registry,
)
from ..utils.faults import TenantFaultError, fault_point
from .base import Checker
from .pipeline import HostPipeline
from .tpu import (
    _DEPTH_INF,
    _MAX_LOAD,
    _pow2ceil,
    bucket_for,
    bucket_ladder_widths,
    checkpoint_header,
    packed_model_digest,
    shared_aot_cache,
    validate_checkpoint_header,
)

__all__ = ["TenantPackedEngine", "TenantRun"]

# Fixed batch width for bulk (resume-admission) inserts: one compile
# serves every restored payload regardless of its key count.
_BULK_INSERT_WIDTH = 1 << 13


class _LaneStore:
    """One tenant's pending frontier: a FIFO of dense host-side lane
    blocks (numpy struct-of-arrays: states pytree + hi/lo/ebits/depth).
    Push (async verdict worker) and take (engine thread) are guarded by
    a lock; blocks are immutable once pushed."""

    def __init__(self):
        self._blocks = deque()
        self._lock = threading.Lock()
        self.pending = 0

    def push(self, block: dict, n: int) -> None:
        if n == 0:
            return
        with self._lock:
            self._blocks.append((block, n))
            self.pending += n

    def take(self, k: int) -> List[dict]:
        """Up to ``k`` lanes off the head, as dense blocks (a partially
        consumed block is split; FIFO lane order is preserved)."""
        out = []
        with self._lock:
            while k > 0 and self._blocks:
                block, n = self._blocks.popleft()
                if n <= k:
                    out.append(block)
                    self.pending -= n
                    k -= n
                else:
                    head = _slice_block(block, 0, k)
                    tail = _slice_block(block, k, n)
                    out.append(head)
                    self._blocks.appendleft((tail, n - k))
                    self.pending -= k
                    k = 0
        return out

    def clear(self) -> None:
        with self._lock:
            self._blocks.clear()
            self.pending = 0


def _slice_block(block: dict, start: int, stop: int) -> dict:
    return {
        k: (
            jax.tree_util.tree_map(lambda x: x[start:stop], v)
            if k == "states"
            else v[start:stop]
        )
        for k, v in block.items()
    }


class _Tenant:
    """One resident tenant's host state (slot, salt, frontier, ledgers)."""

    def __init__(self, key, run_id, slot, epoch, depth_cap, registry):
        self.key = key
        self.run_id = run_id
        self.slot = slot
        self.salt_hi, self.salt_lo = tenant_salt_pair(epoch)
        self.depth_cap = depth_cap if depth_cap is not None else _DEPTH_INF
        self.registry = registry
        self.instruments = TenantInstruments("pack", registry=registry)
        self.lanes = _LaneStore()
        self.state_count = 0
        self.unique_count = 0
        self.max_depth = 0
        self.discoveries_fp: Dict[str, int] = {}
        # (child u64, parent u64) arrays per wave — the parent-pointer
        # stream (path reconstruction + the preempt payload + the
        # eviction attribution source).
        self.wave_log: List = []
        self._ingested = 0
        self._ingest_lock = threading.Lock()
        self.store = make_fingerprint_store()
        # Unsalted fps claimed fresh in L0 since the last eviction —
        # exactly what an eviction must drain into this tenant's
        # partition.
        self.resident: List[np.ndarray] = []
        self.done = False      # no further lanes scheduled
        self.finished = False  # reported complete (view.is_done)
        # A fault was attributed to this tenant: it is rolled back to
        # its pre-wave boundary, excluded from further scheduling, and
        # waits for the service to drop() it (its payload slice is
        # exact — see TenantPackedEngine._tenant_rollback).
        # ``fault_error`` keeps THIS tenant's own exception — several
        # tenants can fault in one wave, and each one's retry filter
        # and flight dump must see its own error, not the first's.
        self.faulted = False
        self.fault_error: Optional[BaseException] = None
        self.compile_offset = 0.0
        self.view: Optional["TenantRun"] = None
        # Device liveness (engine liveness="device"): this tenant's own
        # condition-false edge partition + the finish-time verdicts.
        # Absorbs are idempotent facts about the state graph (the store
        # dedups), so fault rollback never needs to undo them.
        self.live_store = None
        self.live_paths: Dict[str, "Path"] = {}
        self.live_outcomes: Dict[str, dict] = {}

    def ingest(self) -> None:
        with self._ingest_lock:
            while self._ingested < len(self.wave_log):
                children, parents = self.wave_log[self._ingested]
                self.store.insert_batch(children, parents)
                self._ingested += 1


class TenantRun(Checker):
    """The caller-facing handle for one packed tenant — the standard
    ``Checker`` surface (counts, discoveries with reconstructed paths,
    golden reporter, assertions) over the engine's per-tenant state, so
    the service finalizes a packed job exactly like a solo one."""

    supports_preempt = True  # preemption == lane drop, engine-mediated

    def __init__(self, engine: "TenantPackedEngine", tenant: _Tenant):
        self._engine = engine
        self._t = tenant
        self.run_id = tenant.run_id
        self._registry = tenant.registry
        self.warmup_seconds = 0.0
        # Liveness surfaces (checker/base.py) read these per tenant.
        self._live = engine._live
        self._live_enabled = engine._live_enabled

    supports_device_liveness = True

    @property
    def _live_store(self):
        return self._t.live_store

    @property
    def _live_paths(self):
        return self._t.live_paths

    @property
    def _live_outcomes(self):
        return self._t.live_outcomes

    def model(self):
        return self._engine._model

    def state_count(self) -> int:
        return max(self._t.state_count, self._t.unique_count)

    def unique_state_count(self) -> int:
        return self._t.unique_count

    def max_depth(self) -> int:
        return self._t.max_depth

    def discoveries(self) -> Dict[str, Path]:
        out = {
            name: self._reconstruct(fp)
            for name, fp in list(self._t.discoveries_fp.items())
        }
        return self._with_device_liveness(out)

    def _discovery_names(self) -> List[str]:
        return list(set(self._t.discoveries_fp) | set(self._t.live_paths))

    def _reconstruct(self, fp: int) -> Path:
        self._t.ingest()
        chain = self._t.store.chain(fp)
        return Path.from_fingerprints(
            self.model(), chain, fp_of=self._engine._host_fp
        )

    def handles(self) -> List[threading.Thread]:
        return []

    def is_done(self) -> bool:
        return self._t.finished

    def worker_error(self) -> Optional[BaseException]:
        return None

    def state_digest(self) -> dict:
        digest = super().state_digest()
        digest.update(
            packed=True,
            tenant_slot=self._t.slot,
            pending_lanes=self._t.lanes.pending,
            preempted=self.preempted,
        )
        return digest


class TenantPackedEngine:
    """The packer: shared table + shared wave executables, per-tenant
    lane accounting. Driven wave-at-a-time by one caller thread (the
    service scheduler): ``admit()`` claims a lane slot (optionally
    restoring a checkpoint-v2 payload), ``step()`` advances every
    resident tenant by one packed wave and returns the tenants that
    completed, ``drop()`` preempts one tenant into a payload slice.

    ``aot_cache`` (a namespace string) shares the wave/seed/rehash
    executables process-globally, so a later engine instance for the
    same pack configuration compiles nothing (same discipline as
    ``TpuBfsChecker``'s shared AOT cache).
    """

    def __init__(
        self,
        model,
        *,
        frontier_capacity: int = 1 << 10,
        table_capacity: int = 1 << 16,
        max_tenants: int = 8,
        bucket_ladder: Optional[int] = None,
        hbm_budget_mib: Optional[float] = None,
        host_budget_mib: Optional[float] = None,
        spill_dir: Optional[str] = None,
        async_pipeline: bool = False,
        aot_cache: Optional[str] = None,
        resume_capacity: Optional[int] = None,
        run_id: Optional[str] = None,
        liveness=None,
    ):
        if not isinstance(model, BatchableModel):
            raise TypeError(
                "TenantPackedEngine requires a BatchableModel; "
                f"{type(model).__name__} does not implement the packed "
                "protocol"
            )
        self._model = model
        self._properties = model.properties()
        self._conditions = model.packed_conditions()
        if len(self._conditions) != len(self._properties):
            raise ValueError(
                "packed_conditions() must align 1:1 with properties(): "
                f"{len(self._conditions)} != {len(self._properties)}"
            )
        eventually = [
            i
            for i, p in enumerate(self._properties)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if len(eventually) > 32:
            raise ValueError("at most 32 eventually properties supported")
        self._ebit = {pi: b for b, pi in enumerate(eventually)}
        self._ebits0 = sum(1 << b for b in self._ebit.values())
        # Device-native liveness, packed: the wave logs each lane's
        # condition-false edges with its tenant id, and the verdict
        # splits them into PER-TENANT host edge partitions — fps are
        # the ORIGINAL (pre-salt) ones (chi/clo are computed before
        # ``hashset_insert_salted`` applies the XOR), so each tenant's
        # relation is bit-identical to its solo run's and the per-tenant
        # trim/reach verdict at finish time matches the solo verdict
        # exactly (tests/test_device_liveness.py).
        from .device_liveness import LIVENESS_MODES

        if liveness not in LIVENESS_MODES:
            raise ValueError(
                f"liveness must be one of {LIVENESS_MODES}, "
                f"got {liveness!r}"
            )
        self._live = "device" if liveness == "device" else None
        self._live_enabled = self._live == "device" and bool(self._ebit)
        self._A = model.packed_action_count()
        self._fp_fn = model.packed_fingerprint
        self._K = max(1, int(max_tenants))
        self._F_max = _pow2ceil(frontier_capacity)
        from .tpu import _AUTO_BUCKET_MIN_F, _DEFAULT_BUCKET_STEPS

        if bucket_ladder is None:
            bucket_ladder = (
                _DEFAULT_BUCKET_STEPS
                if self._F_max >= _AUTO_BUCKET_MIN_F
                else 0
            )
        self._buckets = bucket_ladder_widths(self._F_max, bucket_ladder)
        self._capacity = _pow2ceil(table_capacity)
        self._resume_capacity = resume_capacity or table_capacity

        from ..storage import (
            TenantPartitions,
            max_table_rows_for_budget,
            validate_budget_knobs,
        )

        validate_budget_knobs(hbm_budget_mib, host_budget_mib, spill_dir)
        self._max_capacity = None
        if hbm_budget_mib is not None:
            max_cap = max_table_rows_for_budget(hbm_budget_mib)
            min_cap = _pow2ceil(int(self._F_max * self._A / _MAX_LOAD) + 1)
            if max_cap < min_cap:
                raise ValueError(
                    f"hbm_budget_mib={hbm_budget_mib} allows a device "
                    f"table of {max_cap} rows, but one worst-case packed "
                    f"wave needs at least {min_cap}; raise the budget or "
                    "shrink frontier_capacity"
                )
            self._max_capacity = max_cap
            self._capacity = min(self._capacity, max_cap)
        self.run_id = run_id
        self._registry = metrics_registry(run_id) if run_id else None
        self._tracer = get_tracer(run_id)
        self._partitions = TenantPartitions(
            host_budget_mib=host_budget_mib,
            spill_dir=spill_dir,
            tracer=self._tracer,
        )
        self._wi = WaveInstruments("pack", registry=self._registry)
        reg = (
            self._registry
            if self._registry is not None
            else metrics_registry()
        )
        # Lane accounting: dispatched = width x waves (what the device
        # executed), live = real tenant lanes in them. live/dispatched
        # is the pack's occupancy — the whole point of packing.
        self._c_lanes_dispatched = reg.counter("pack.lanes_dispatched")
        self._c_lanes_live = reg.counter("pack.lanes_live")

        self._table = hashset_new(self._capacity)
        self._l0 = 0
        self._slots: List[Optional[_Tenant]] = [None] * self._K
        self._by_key: Dict[object, _Tenant] = {}
        self._salt_epochs = itertools.count(1)
        self._rr = 0  # rotating lane-allocation offset (fairness)
        self.waves = 0
        self.compile_seconds = 0.0
        self.lanes_dispatched = 0
        self.lanes_live = 0

        self._pipe = (
            HostPipeline(name="pack-host") if async_pipeline else None
        )

        # Host-side state template (per-lane leaf shapes/dtypes) for
        # frontier assembly; the treedef is the packed pytree structure.
        init_np = jax.tree_util.tree_map(
            np.asarray, model.packed_init_states()
        )
        leaves, treedef = jax.tree_util.tree_flatten(init_np)
        self._state_treedef = treedef
        self._leaf_specs = [(x.shape[1:], x.dtype) for x in leaves]

        # Executables: (kind, *shape) -> AOT-compiled fn; process-global
        # under a namespace so engines for one pack config never
        # recompile.
        if aot_cache is not None:
            self._exec = shared_aot_cache(
                aot_cache, ("packed_tenancy",) + self._aot_signature()
            )
        else:
            self._exec = {}
        self._jit_wave = jax.jit(self._wave, donate_argnums=(0,))
        self._jit_seed = jax.jit(self._seed_wave, donate_argnums=(0,))
        self._jit_bulk = jax.jit(self._bulk_insert, donate_argnums=(0,))
        self._jit_rehash = jax.jit(self._rehash, donate_argnums=(1,))
        self._jit_fp_single = jax.jit(self._fp_fn)

    # -- identity ----------------------------------------------------------

    def _aot_signature(self) -> tuple:
        return (
            jax.default_backend(),
            packed_model_digest(self._model, self._A),
            tuple((p.name, str(p.expectation)) for p in self._properties),
            self._K,
            self._F_max,
            tuple(self._buckets),
            self._max_capacity,
            self._live_enabled,
        )

    def _host_fp(self, host_state) -> int:
        hi, lo = self._jit_fp_single(self._model.pack_state(host_state))
        return fp_to_int(hi, lo)

    # -- device functions (jitted) -----------------------------------------

    def _wave(self, table, states, hi, lo, ebits, depth, mask, tid,
              salt_hi, salt_lo, depth_caps):
        """One packed wave over ``F`` mixed-tenant lanes: the solo
        materializing wave body (checker/tpu.py ``_wave``) with a
        tenant-lane dimension — per-lane depth caps, salted claims, and
        per-tenant (one-hot segmented) reductions."""
        model = self._model
        A, K = self._A, self._K
        F = hi.shape[0]
        B = F * A
        eval_mask = mask & (depth < depth_caps[tid])

        cond_vals = [jax.vmap(c)(states) for c in self._conditions]
        ebits_after = ebits
        for pi, b in self._ebit.items():
            ebits_after = jnp.where(
                cond_vals[pi], ebits_after & ~jnp.uint32(1 << b), ebits_after
            )

        cand, cvalid = jax.vmap(model.packed_expand)(states)
        cvalid = cvalid & eval_mask[:, None]
        cvalid = cvalid & jax.vmap(
            jax.vmap(model.packed_within_boundary)
        )(cand)
        terminal = eval_mask & ~cvalid.any(axis=1)

        cand_flat = jax.tree_util.tree_map(
            lambda x: x.reshape((B,) + x.shape[2:]), cand
        )
        cvalid_flat = cvalid.reshape(B)
        chi, clo = jax.vmap(self._fp_fn)(cand_flat)
        lanes = jnp.arange(B, dtype=jnp.int32)
        parent_row = lanes // A
        ctid = tid[parent_row]
        # Salted claim in the one shared table; natural lane order is
        # preserved (see module docstring for why that is the whole
        # bit-identity story).
        table, fresh, _found, pending = hashset_insert_salted(
            table, chi, clo, salt_hi[ctid], salt_lo[ctid], cvalid_flat
        )
        overflow = pending.sum()

        # Per-tenant segmented reductions (K is small and static: the
        # one-hot forms fuse into a handful of masked sums).
        slot_ids = jnp.arange(K, dtype=jnp.int32)
        onehot_f = (tid[:, None] == slot_ids[None, :]) & mask[:, None]
        gen_lane = cvalid.sum(axis=1, dtype=jnp.int32)
        gen_t = jnp.sum(
            jnp.where(onehot_f, gen_lane[:, None], 0), axis=0,
            dtype=jnp.int32,
        )
        maxd_t = jnp.max(
            jnp.where(onehot_f, depth[:, None], 0), axis=0
        ).astype(jnp.int32)
        onehot_b = (ctid[:, None] == slot_ids[None, :]) & fresh[:, None]
        new_t = jnp.sum(onehot_b, axis=0, dtype=jnp.int32)

        # Fresh lanes compact to a prefix in natural lane order.
        pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        out_slot = jnp.where(fresh, pos, B)
        zi = jnp.zeros((B,), jnp.int32)
        zu = jnp.zeros((B,), jnp.uint32)
        src_idx = zi.at[out_slot].set(lanes, mode="drop")
        new = {
            "hi": zu.at[out_slot].set(chi, mode="drop"),
            "lo": zu.at[out_slot].set(clo, mode="drop"),
            "ebits": zu.at[out_slot].set(
                ebits_after[parent_row], mode="drop"
            ),
            "depth": zi.at[out_slot].set(
                depth[parent_row] + 1, mode="drop"
            ),
            "tid": zi.at[out_slot].set(ctid, mode="drop"),
            "parent_hi": zu.at[out_slot].set(hi[parent_row], mode="drop"),
            "parent_lo": zu.at[out_slot].set(lo[parent_row], mode="drop"),
            "states": jax.tree_util.tree_map(
                lambda x: x[src_idx], cand_flat
            ),
        }

        out = {"table": table, "new": new}
        if self._live_enabled:
            # Per-tenant condition-false edge rows (ORIGINAL fps — the
            # salt never touches chi/clo), tenant id riding each row so
            # the verdict can split them into per-tenant partitions.
            from .device_liveness import wave_edge_rows

            live_rows, live_n = wave_edge_rows(
                self._conditions, self._ebit, cond_vals, cand_flat,
                cvalid_flat, terminal, hi, lo, chi, clo, A,
                extra_lane={"tid": ctid}, extra_row={"tid": tid},
            )
            out["live"] = live_rows
            out["live_n"] = live_n
        # Per-(tenant, property) discovery scan over the evaluated
        # frontier — argmax picks the tenant's FIRST hit in lane order,
        # which is its first hit in its own FIFO order.
        P = len(self._properties)
        if P:
            hits, fhis, flos = [], [], []
            for i, p in enumerate(self._properties):
                if p.expectation == Expectation.ALWAYS:
                    h = eval_mask & ~cond_vals[i]
                elif p.expectation == Expectation.SOMETIMES:
                    h = eval_mask & cond_vals[i]
                else:
                    b = self._ebit[i]
                    h = terminal & (
                        ((ebits_after >> jnp.uint32(b)) & 1) == 1
                    )
                for k in range(K):
                    hk = h & (tid == k)
                    idx = jnp.argmax(hk)
                    hits.append(hk.any())
                    fhis.append(hi[idx])
                    flos.append(lo[idx])
            out["prop_hit"] = jnp.stack(hits).reshape(P, K)
            out["prop_hi"] = jnp.stack(fhis).reshape(P, K)
            out["prop_lo"] = jnp.stack(flos).reshape(P, K)

        stats = [overflow.astype(jnp.int32)]
        if P:
            stats.append(out["prop_hit"].any().astype(jnp.int32))
        else:
            stats.append(jnp.int32(0))
        cols = [jnp.stack(stats), gen_t, new_t, maxd_t]
        if self._live_enabled:
            cols.append(out["live_n"][None].astype(jnp.int32))
        out["stats"] = jnp.concatenate(cols)
        return out

    def _seed_wave(self, table, salt_hi, salt_lo):
        """Claims one tenant's init states in the shared table (salted);
        mirrors the solo ``_init_wave``'s counting exactly (duplicate
        valid inits resolve to one fresh claim)."""
        model = self._model
        states = model.packed_init_states()
        valid = jax.vmap(model.packed_within_boundary)(states)
        hi, lo = jax.vmap(self._fp_fn)(states)
        n0 = hi.shape[0]
        table, fresh, _found, pending = hashset_insert_salted(
            table,
            hi,
            lo,
            jnp.full((n0,), salt_hi, jnp.uint32),
            jnp.full((n0,), salt_lo, jnp.uint32),
            valid,
        )
        out = {
            "table": table,
            "states": states,
            "valid": valid,
            "hi": hi,
            "lo": lo,
            "n_unique": fresh.sum(dtype=jnp.int32),
            "n_valid": valid.sum(dtype=jnp.int32),
            "overflow": pending.sum(dtype=jnp.int32),
        }
        if self._live_enabled:
            from .device_liveness import seed_root_mask

            out["root_mask"] = seed_root_mask(
                self._conditions, self._ebit, states, valid
            )
        return out

    def _bulk_insert(self, table, hi, lo, salt_hi, salt_lo, active):
        """Fixed-width salted claim batch (resume admission)."""
        n = hi.shape[0]
        table, fresh, _found, pending = hashset_insert_salted(
            table,
            hi,
            lo,
            jnp.full((n,), salt_hi, jnp.uint32),
            jnp.full((n,), salt_lo, jnp.uint32),
            active,
        )
        return table, fresh.sum(dtype=jnp.int32), pending.sum(
            dtype=jnp.int32
        )

    def _rehash(self, old_table, new_table):
        from ..ops.hashset import hashset_insert

        active = (old_table[:, 0] != 0) | (old_table[:, 1] != 0)
        new_table, _fresh, _found, pending = hashset_insert(
            new_table, old_table[:, 0], old_table[:, 1], active
        )
        return new_table, pending.sum()

    # -- AOT dispatch ------------------------------------------------------

    def _compiled(self, kind, jit_fn, args, key_extra=()):
        key = (kind,) + tuple(key_extra)
        exe = self._exec.get(key)
        if exe is None:
            t0 = time.perf_counter()
            with self._tracer.span("pack.compile", kind=kind):
                exe = jit_fn.lower(*args).compile()
            self._exec[key] = exe
            self.compile_seconds += time.perf_counter() - t0
            self._wi.warmup.set(self.compile_seconds)
        return exe

    # -- membership --------------------------------------------------------

    def free_slots(self) -> int:
        return sum(1 for s in self._slots if s is None)

    def live_count(self) -> int:
        return sum(
            1
            for s in self._slots
            if s is not None and not s.finished
        )

    def tenants(self) -> List[_Tenant]:
        return [s for s in self._slots if s is not None]

    def view(self, key) -> Optional[TenantRun]:
        t = self._by_key.get(key)
        return t.view if t is not None else None

    def admit(self, key, run_id=None, *, depth_cap=None,
              resume_from=None) -> TenantRun:
        """Claims a free lane slot for one tenant. ``resume_from`` is a
        checkpoint-v2 payload (a prior ``drop()``'s slice, or a solo
        ``TpuBfsChecker`` preempt payload of the same model config):
        counters, discoveries, the parent log, the pending frontier, and
        any storage partition restore; the tenant's known keys bulk-claim
        salted slots under a FRESH salt epoch, so leftovers of departed
        tenants can never alias it."""
        if key in self._by_key:
            raise ValueError(f"tenant {key!r} is already packed")
        if depth_cap is not None and self._live_enabled:
            raise ValueError(
                "liveness='device' packs cannot admit depth-capped "
                "tenants: a capped exploration logs a truncated edge "
                "relation, so the finish-time verdict would be unsound"
            )
        slot = next(
            (i for i, s in enumerate(self._slots) if s is None), None
        )
        if slot is None:
            raise RuntimeError(
                f"no free lanes (max_tenants={self._K})"
            )
        registry = metrics_registry(run_id) if run_id else (
            self._registry or metrics_registry()
        )
        t = _Tenant(
            key, run_id, slot, next(self._salt_epochs), depth_cap, registry
        )
        t.compile_offset = self.compile_seconds
        # Register BEFORE seeding/restoring: a budget-capped eviction
        # fired by the admission's own table claims must flush THIS
        # tenant's resident keys into its partition too — an
        # unregistered tenant's earlier-batch claims would vanish from
        # the reset table and be silently re-counted as fresh later.
        self._slots[slot] = t
        self._by_key[key] = t
        try:
            if resume_from is not None:
                self._restore_tenant(t, resume_from)
            else:
                self._seed_tenant(t)
        except BaseException:
            self._slots[slot] = None
            del self._by_key[key]
            self._partitions.drop(key)
            raise
        if not self._properties:
            # Nothing to discover: mirror the solo wave loop's immediate
            # exit after seeding.
            t.done = True
        t.instruments.joins.inc()
        t.view = TenantRun(self, t)
        self._tracer.instant(
            "pack.tenant_join", tenant=str(key), slot=slot,
            resumed=resume_from is not None,
        )
        return t.view

    def _seed_tenant(self, t: _Tenant) -> None:
        # Fresh claims accumulate across growth retries: the shared
        # table cannot be reset between attempts (other tenants live in
        # it), so a retry's already-claimed inits report found, not
        # fresh, and the attempts' fresh counts sum to the solo seed's.
        n_unique = 0
        attempt = 0
        while True:
            exe = self._compiled(
                "seed", self._jit_seed,
                (self._table, jnp.uint32(t.salt_hi), jnp.uint32(t.salt_lo)),
                (self._table.shape[0],),
            )
            out = exe(
                self._table, jnp.uint32(t.salt_hi), jnp.uint32(t.salt_lo)
            )
            self._table = out["table"]
            n_unique += int(out["n_unique"])
            if not int(out["overflow"]):
                break
            attempt += 1
            if attempt > 8:
                raise RuntimeError(
                    "packed seeding overflowed the shared table"
                )
            self._grow(self._capacity * 2)
        t.state_count = int(out["n_valid"])
        self._l0 += n_unique
        hi = np.asarray(out["hi"])
        lo = np.asarray(out["lo"])
        valid = np.asarray(out["valid"])
        child64 = fp64_pairs(hi, lo)[valid]
        # Count distinct inits host-side: exact even if a mid-seed
        # eviction (budget mode) forced claims to repeat.
        t.unique_count = int(len(np.unique(child64)))
        t.wave_log.append((child64, np.zeros_like(child64)))
        t.resident.append(np.unique(child64))
        if self._live_enabled:
            self._live_tenant_store(t).add_roots(
                child64, np.asarray(out["root_mask"])[valid]
            )
        states_np = jax.tree_util.tree_map(np.asarray, out["states"])
        n_live = int(valid.sum())
        block = {
            "states": jax.tree_util.tree_map(
                lambda x: x[valid], states_np
            ),
            "hi": hi[valid],
            "lo": lo[valid],
            "ebits": np.full((n_live,), self._ebits0, np.uint32),
            "depth": np.ones((n_live,), np.int32),
        }
        t.lanes.push(block, n_live)

    def _restore_tenant(self, t: _Tenant, payload: dict) -> None:
        validate_checkpoint_header(
            payload,
            "tpu_bfs",
            "packed admission restores single-device payloads only",
            self._model,
            self._A,
            False,
            None,
        )
        t.state_count = payload["state_count"]
        t.unique_count = payload["unique_count"]
        t.max_depth = payload["max_depth"]
        t.discoveries_fp = dict(payload["discoveries"])
        children = payload["children"]
        parents = payload["parents"]
        t.wave_log.append((children, parents))
        keys = np.unique(np.asarray(children, np.uint64))
        storage_state = payload.get("storage")
        if storage_state:
            store = self._partitions.store(t.key, registry=t.registry)
            store.load_state(storage_state)
            keys = keys[~store.probe(keys)]
        t.resident.append(keys)
        # Liveness edge partition must round-trip with the tenant (see
        # checker/tpu.py for why mode mismatches are refused).
        live_state = payload.get("liveness")
        if self._live_enabled and live_state is None:
            raise ValueError(
                "liveness='device' packs cannot admit a payload written "
                "without it: pre-checkpoint edges were never logged, so "
                "the finish-time verdict would be unsound"
            )
        if live_state is not None:
            if not self._live_enabled:
                raise ValueError(
                    "payload carries a liveness edge store; admit into "
                    "a liveness='device' pack (or resume solo with "
                    "liveness='device')"
                )
            self._live_tenant_store(t).load_state(live_state)
        # Bulk-claim the tenant's known keys under its fresh salt.
        hi = (keys >> np.uint64(32)).astype(np.uint32)
        lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        W = _BULK_INSERT_WIDTH
        for start in range(0, len(keys), W):
            bh = np.zeros((W,), np.uint32)
            bl = np.zeros((W,), np.uint32)
            act = np.zeros((W,), bool)
            n = min(W, len(keys) - start)
            bh[:n] = hi[start : start + n]
            bl[:n] = lo[start : start + n]
            act[:n] = True
            attempt = 0
            while True:
                args = (
                    self._table,
                    jnp.asarray(bh),
                    jnp.asarray(bl),
                    jnp.uint32(t.salt_hi),
                    jnp.uint32(t.salt_lo),
                    jnp.asarray(act),
                )
                exe = self._compiled(
                    "bulk", self._jit_bulk, args,
                    (self._table.shape[0],),
                )
                self._table, fresh_n, pend = exe(*args)
                self._l0 += int(fresh_n)
                if not int(pend):
                    break
                attempt += 1
                if attempt > 8:
                    raise RuntimeError(
                        "packed admission overflowed the shared table"
                    )
                self._grow(self._capacity * 2)
        for chunk in payload["chunks"]:
            mask = np.asarray(chunk["mask"])
            n = int(mask.sum())
            if n == 0:
                continue
            block = {
                "states": jax.tree_util.tree_map(
                    lambda x: np.asarray(x)[mask], chunk["states"]
                ),
                "hi": np.asarray(chunk["hi"])[mask],
                "lo": np.asarray(chunk["lo"])[mask],
                "ebits": np.asarray(chunk["ebits"])[mask],
                "depth": np.asarray(chunk["depth"])[mask],
            }
            t.lanes.push(block, n)
        if self._properties and len(t.discoveries_fp) == len(
            self._properties
        ):
            t.done = True

    def drop(self, key, *, discard: bool = False) -> Optional[dict]:
        """Preempts one tenant by dropping its lanes: no device drain —
        its pending frontier, counters, parent log, and storage
        partition leave as a checkpoint-v2 payload slice (``None`` with
        ``discard=True``, the cancel path). The slot and its lane share
        free immediately; the departed tenant's salted table keys are
        garbage that dies at the next growth rehash or eviction."""
        t = self._by_key.get(key)
        if t is None:
            raise KeyError(f"no packed tenant {key!r}")
        if self._pipe is not None:
            self._pipe.drain()
        t.instruments.lane_drops.inc(t.lanes.pending)
        payload = None
        if not discard and not t.finished:
            payload = self._payload(t)  # consumes the lane store
            if t.view is not None:
                t.view._preempt_payload = payload
        t.lanes.clear()
        self._slots[t.slot] = None
        del self._by_key[t.key]
        self._partitions.drop(t.key)
        self._finish_view(t)
        self._tracer.instant(
            "pack.tenant_drop", tenant=str(t.key), slot=t.slot,
            discarded=discard,
        )
        return payload

    def _payload(self, t: _Tenant) -> dict:
        """The tenant's state as a standard checkpoint-v2 payload —
        loadable by ``TpuBfsChecker(resume_from=...)`` or a later
        ``admit(resume_from=...)``, bit-identically either way."""
        t.ingest()
        children, parents = t.store.export()
        chunks = []
        F = self._F_max
        blocks = t.lanes.take(t.lanes.pending)
        lanes_np = _concat_blocks(blocks, self._leaf_specs,
                                  self._state_treedef)
        if lanes_np is not None:
            total = len(lanes_np["hi"])
            for start in range(0, total, F):
                n = min(F, total - start)
                piece = _slice_block(lanes_np, start, start + n)
                chunk = {
                    "states": jax.tree_util.tree_map(
                        lambda x: _pad_rows(x, F), piece["states"]
                    ),
                    "hi": _pad_rows(piece["hi"], F),
                    "lo": _pad_rows(piece["lo"], F),
                    "ebits": _pad_rows(piece["ebits"], F),
                    "depth": _pad_rows(piece["depth"], F),
                    "mask": np.arange(F, dtype=np.int32) < n,
                }
                chunks.append(chunk)
        payload = {
            **checkpoint_header(
                "tpu_bfs", self._model, self._A, False, None
            ),
            "state_count": t.state_count,
            "unique_count": t.unique_count,
            "max_depth": t.max_depth,
            "discoveries": dict(t.discoveries_fp),
            "children": children,
            "parents": parents,
            "capacity": self._resume_capacity,
            "chunks": chunks,
        }
        store = self._partitions.get(t.key)
        if store is not None and not store.is_empty():
            payload["storage"] = store.export_state()
        if self._live_enabled:
            payload["liveness"] = self._live_tenant_store(
                t
            ).export_state()
            payload["version"] = 3
        return payload

    def _finish_view(self, t: _Tenant) -> None:
        if t.view is not None:
            t.view.warmup_seconds = max(
                0.0, self.compile_seconds - t.compile_offset
            )

    # -- table management --------------------------------------------------

    def _grow(self, min_capacity: int) -> None:
        if (
            self._max_capacity is not None
            and min_capacity > self._max_capacity
        ):
            self._evict()
            return
        capacity = self._capacity
        while capacity < min_capacity:
            capacity *= 2
        while True:
            args = (self._table, hashset_new(capacity))
            exe = self._compiled(
                "rehash", self._jit_rehash, args,
                (self._table.shape[0], capacity),
            )
            with self._tracer.span(
                "pack.table_grow", from_capacity=self._capacity,
                to_capacity=capacity,
            ):
                new_table, leftover = exe(*args)
            if not int(leftover):
                break
            capacity *= 2
            if (
                self._max_capacity is not None
                and capacity > self._max_capacity
            ):
                self._evict()
                return
        self._table = new_table
        self._capacity = capacity
        self._wi.table_grows.inc()
        self._wi.capacity.set(capacity)

    def _evict(self) -> None:
        """Budget-capped growth: drains every tenant's since-eviction L0
        claims into its own partition and resets the shared table. The
        pipeline drains first so in-flight verdicts land their keys
        before the flush (the FIFO merge fence, engine-side)."""
        if self._pipe is not None:
            self._pipe.drain()
        deferred: Optional[TenantFaultError] = None
        for t in self.tenants():
            if t.resident and not t.faulted:
                try:
                    # Injection seam: one tenant's partition eviction
                    # dies (spill ENOSPC included — the partition store
                    # carries the same owner tag). Contained: the other
                    # tenants' claims still absorb, and the faulted
                    # tenant's payload rebuilds its visited set from
                    # the parent log, not from `resident`.
                    fault_point("pack.tenant.evict", tenant=t.key)
                    fps = np.unique(np.concatenate(t.resident))
                    if len(fps):
                        self._partitions.store(
                            t.key, registry=t.registry
                        ).evict(fps)
                    t.resident = []
                except BaseException as e:  # noqa: BLE001 - per-tenant
                    t.faulted = True
                    t.fault_error = e
                    if deferred is None:
                        deferred = TenantFaultError(t.key, e)
                        deferred.__cause__ = e
        self._capacity = self._max_capacity
        self._table = hashset_new(self._capacity)
        self._l0 = 0
        self._wi.capacity.set(self._capacity)
        self._tracer.instant("pack.evict", capacity=self._capacity)
        if deferred is not None:
            raise deferred

    # -- the packed wave loop ----------------------------------------------

    def _quotas(self, ready: List[_Tenant], width: int) -> Dict[int, int]:
        """Deterministic fair lane split: equal base share in rotating
        slot order, leftovers greedily to tenants with deeper backlogs."""
        order = sorted(
            ready, key=lambda t: (t.slot - self._rr) % self._K
        )
        self._rr = (self._rr + 1) % self._K
        base = max(1, width // len(order))
        q: Dict[int, int] = {}
        rem = width
        for t in order:
            share = min(t.lanes.pending, base, rem)
            q[t.slot] = share
            rem -= share
        for t in order:
            if rem <= 0:
                break
            extra = min(t.lanes.pending - q[t.slot], rem)
            q[t.slot] += extra
            rem -= extra
        return q

    def _assemble(self, ready: List[_Tenant]):
        total = sum(t.lanes.pending for t in ready)
        width = bucket_for(self._buckets, max(1, min(total, self._F_max)))
        quotas = self._quotas(ready, width)
        tid = np.zeros((width,), np.int32)
        mask = np.zeros((width,), bool)
        hi = np.zeros((width,), np.uint32)
        lo = np.zeros((width,), np.uint32)
        ebits = np.zeros((width,), np.uint32)
        depth = np.zeros((width,), np.int32)
        leaves = [
            np.zeros((width,) + shape, dtype)
            for shape, dtype in self._leaf_specs
        ]
        cursor = 0
        lanes_by_slot: Dict[int, int] = {}
        for t in sorted(ready, key=lambda t: t.slot):
            take = quotas.get(t.slot, 0)
            if take <= 0:
                continue
            got = 0
            for block in t.lanes.take(take):
                n = len(block["hi"])
                sl = slice(cursor, cursor + n)
                hi[sl] = block["hi"]
                lo[sl] = block["lo"]
                ebits[sl] = block["ebits"]
                depth[sl] = block["depth"]
                tid[sl] = t.slot
                mask[sl] = True
                for dst, src in zip(
                    leaves, jax.tree_util.tree_leaves(block["states"])
                ):
                    dst[sl] = src
                cursor += n
                got += n
            lanes_by_slot[t.slot] = got
        states = jax.tree_util.tree_unflatten(self._state_treedef, leaves)
        return (
            width,
            lanes_by_slot,
            dict(
                states=states, hi=hi, lo=lo, ebits=ebits, depth=depth,
                mask=mask, tid=tid,
            ),
        )

    def _salt_arrays(self):
        sh = np.zeros((self._K,), np.uint32)
        sl = np.zeros((self._K,), np.uint32)
        dc = np.full((self._K,), _DEPTH_INF, np.int32)
        for t in self.tenants():
            sh[t.slot] = t.salt_hi
            sl[t.slot] = t.salt_lo
            dc[t.slot] = min(t.depth_cap, _DEPTH_INF)
        return sh, sl, dc

    def _schedulable(self) -> List[_Tenant]:
        return [
            t
            for t in self.tenants()
            if not t.done and not t.finished and not t.faulted
            and t.lanes.pending > 0
        ]

    def _tenant_snapshot(self, t: _Tenant) -> dict:
        """Everything one wave can mutate for a tenant, captured BEFORE
        ``_assemble`` consumes its lanes. Blocks are immutable once
        pushed, so snapshotting the deque as a list of references is
        exact and cheap — a fault rolls the tenant back to this
        boundary bit-identically (the fault-containment contract)."""
        with t.lanes._lock:
            blocks = list(t.lanes._blocks)
        return dict(
            state_count=t.state_count,
            unique_count=t.unique_count,
            max_depth=t.max_depth,
            discoveries=dict(t.discoveries_fp),
            wave_log_len=len(t.wave_log),
            resident_len=len(t.resident),
            lane_blocks=blocks,
            done=t.done,
        )

    def _tenant_rollback(self, t: _Tenant, snap: dict) -> None:
        """Restores a tenant to its pre-wave snapshot after a fault:
        scalars and discoveries rewind, append-only logs truncate, and
        the lane deque is restored wholesale (consumed inputs included,
        survivor pushes dropped), so ``drop()`` hands back the exact
        last-good-wave-boundary payload. ``resident`` only truncates —
        an eviction that replaced it with [] absorbed those keys into
        the partition, which must not be undone."""
        t.state_count = snap["state_count"]
        t.unique_count = snap["unique_count"]
        t.max_depth = snap["max_depth"]
        t.discoveries_fp = dict(snap["discoveries"])
        del t.wave_log[snap["wave_log_len"]:]
        del t.resident[snap["resident_len"]:]
        with t.lanes._lock:
            t.lanes._blocks = deque(snap["lane_blocks"])
            t.lanes.pending = sum(n for _b, n in snap["lane_blocks"])
        t.done = snap["done"]

    def step(self) -> List[object]:
        """One packed wave (or a finish pass when no lanes are pending).
        Returns the tenant keys that COMPLETED during this step; fetch
        their ``view()`` for verdicts. Raises on engine errors — the
        caller owns failure routing. A :class:`TenantFaultError`
        (synchronous mode) is the blast-radius contract: the named
        tenant is rolled back to its pre-wave boundary and excluded
        from scheduling (drop it for its exact payload slice) while
        every other tenant's state is already consistent — the caller
        keeps stepping the survivors. In async-pipeline mode faults
        surface as pipeline poisoning and are never attributable (the
        poisoned worker skips later tenants' verdicts), so callers must
        treat them engine-wide."""
        ready = self._schedulable()
        if not ready:
            if self._pipe is not None and self._pipe.pending():
                # Survivors may still be in flight; only an empty queue
                # AFTER the barrier means a tenant is exhausted.
                self._pipe.drain()
                ready = self._schedulable()
            if not ready:
                return self._finish_idle()
        if self._pipe is not None:
            self._pipe.throttle()
        snaps = {t.slot: (t, self._tenant_snapshot(t)) for t in ready}
        width, lanes_by_slot, frontier = self._assemble(ready)
        sh, sl, dc = self._salt_arrays()
        self.waves += 1
        self.lanes_live += sum(lanes_by_slot.values())
        self.lanes_dispatched += width
        self._c_lanes_live.inc(sum(lanes_by_slot.values()))
        self._c_lanes_dispatched.inc(width)
        try:
            with self._tracer.span(
                "pack.wave", wave=self.waves, bucket=width,
                tenants=len(lanes_by_slot),
            ) as span:
                gens, news = self._run_attempts(
                    frontier, width, lanes_by_slot, sh, sl, dc
                )
                self._wi.record(
                    span,
                    frontier=width,
                    generated=int(gens.sum()),
                    n_new=int(news.sum()),
                    occupancy=self._l0 / self._capacity,
                    capacity=self._capacity,
                    max_depth=max(
                        (t.max_depth for t in self.tenants()), default=0
                    ),
                    bucket=width,
                    compaction_ratio=sum(lanes_by_slot.values()) / width,
                    tenants=len(lanes_by_slot),
                )
        except TenantFaultError as e:
            if self._pipe is None:
                if e.pre_dispatch:
                    # The wave never executed: every participant's
                    # consumed inputs go back where they came from.
                    for t, snap in snaps.values():
                        self._tenant_rollback(t, snap)
                else:
                    # Roll back EVERY tenant flagged during this wave
                    # (an eviction can fault several at once), not just
                    # the one the raised error names — each must leave
                    # with an exact pre-wave payload.
                    for t, snap in snaps.values():
                        if t.faulted or t.key == e.tenant_key:
                            self._tenant_rollback(t, snap)
                ft = self._by_key.get(e.tenant_key)
                if ft is not None:
                    ft.faulted = True
                self._tracer.instant(
                    "pack.tenant_fault", tenant=str(e.tenant_key),
                    pre_dispatch=e.pre_dispatch,
                )
            raise
        return self._finish_idle()

    def faulted_keys(self) -> List[object]:
        """Every resident tenant currently flagged faulted — the caller
        must drop each one (a single wave can fault several tenants,
        e.g. one eviction pass over every partition); leaving a flagged
        tenant resident would exclude it from scheduling while still
        counting it live."""
        return [t.key for t in self.tenants() if t.faulted]

    def fault_error(self, key) -> Optional[BaseException]:
        """The flagged tenant's OWN exception (each co-faulted tenant
        keeps its own — retry filtering and forensics must not read
        another tenant's error)."""
        t = self._by_key.get(key)
        return t.fault_error if t is not None else None

    def _run_attempts(self, frontier, width, lanes_by_slot, sh, sl, dc):
        """Dispatch + growth-retry loop for one packed wave; returns the
        per-slot (generated, fresh) vectors of the first attempt /
        accumulated fresh."""
        K = self._K
        try:
            self._ensure_capacity(width * self._A)
        except TenantFaultError as e:
            # Pre-dispatch eviction fault: nothing executed yet, so the
            # caller can restore EVERY participant's inputs exactly.
            e.pre_dispatch = True
            raise
        gens = np.zeros((K,), np.int64)
        news = np.zeros((K,), np.int64)
        attempt = 0
        deferred: Optional[TenantFaultError] = None
        while True:
            args = (
                self._table,
                frontier["states"],
                frontier["hi"],
                frontier["lo"],
                frontier["ebits"],
                frontier["depth"],
                frontier["mask"],
                frontier["tid"],
                jnp.asarray(sh),
                jnp.asarray(sl),
                jnp.asarray(dc),
            )
            exe = self._compiled(
                "wave", self._jit_wave, args,
                (self._table.shape[0], width),
            )
            # Injection seam: a packed device-wave raise is inherently
            # engine-wide (every tenant's lanes ride the one dispatch)
            # — the service retries all members solo from their last
            # checkpointed boundaries.
            fault_point("device.wave")
            out = exe(*args)
            self._table = out["table"]
            stats = np.asarray(out["stats"])
            overflow = int(stats[0])
            any_hit = int(stats[1])
            gen_t = stats[2 : 2 + K]
            new_t = stats[2 + K : 2 + 2 * K]
            maxd_t = stats[2 + 2 * K : 2 + 3 * K]
            if attempt == 0:
                gens += gen_t
                self._apply_stats(gen_t, maxd_t, any_hit, out)
            news += new_t
            n_total = int(new_t.sum())
            self._l0 += n_total
            ticket = dict(
                out=out,
                n_total=n_total,
                new_t=new_t,
                gen_t=gen_t if attempt == 0 else np.zeros((K,), np.int64),
                width=width,
                lanes_by_slot=lanes_by_slot if attempt == 0 else {},
                live_n=(
                    int(stats[2 + 3 * K]) if self._live_enabled else 0
                ),
            )
            if self._pipe is None:
                try:
                    self._verdict(ticket)
                except TenantFaultError as e:
                    # Defer: the remaining growth attempts must still
                    # run so every OTHER tenant's wave completes in
                    # full — the faulted tenant (already flagged) is
                    # skipped by later verdicts and rolled back by the
                    # caller.
                    if deferred is None:
                        deferred = e
            else:
                self._pipe.submit(lambda tk=ticket: self._verdict(tk))
            if not overflow:
                if deferred is not None:
                    raise deferred
                return gens, news
            if self._max_capacity is not None and attempt >= 8:
                raise RuntimeError(
                    "a packed wave's candidates overflow the "
                    "budget-capped shared table after repeated "
                    "evictions; raise the budget or shrink "
                    "frontier_capacity"
                )
            try:
                self._grow(self._capacity * 2)
            except TenantFaultError as e:
                # Mid-wave eviction fault: the overflow retry this grow
                # was serving never runs, so EVERY tenant's wave is
                # incomplete — per-tenant attribution would be a lie.
                raise RuntimeError(
                    "packed eviction failed mid-wave (overflow retry "
                    "pending); engine-wide fault"
                ) from e
            attempt += 1

    def _apply_stats(self, gen_t, maxd_t, any_hit, out) -> None:
        """First-attempt caller-side bookkeeping: generated/depth
        counters and per-tenant discovery fingerprints (a tenant whose
        every property is discovered stops scheduling, mirroring the
        solo loop's early exit)."""
        props = self._properties
        hit = phi = plo = None
        if props and any_hit:
            hit = np.asarray(out["prop_hit"])
            phi = np.asarray(out["prop_hi"])
            plo = np.asarray(out["prop_lo"])
        for t in self.tenants():
            k = t.slot
            t.state_count += int(gen_t[k])
            t.max_depth = max(t.max_depth, int(maxd_t[k]))
            if hit is not None:
                for i, p in enumerate(props):
                    if hit[i, k] and p.name not in t.discoveries_fp:
                        t.discoveries_fp[p.name] = fp_to_int(
                            phi[i, k], plo[i, k]
                        )
                if len(t.discoveries_fp) == len(props) and not t.done:
                    t.done = True
                    t.lanes.clear()

    def _verdict(self, ticket: dict) -> None:
        """One wave attempt's host half (pipeline worker in async mode):
        per-tenant partition probe, parent-log append, survivor
        re-entry at each tenant's queue tail, lane-accounting metrics."""
        n_total = ticket["n_total"]
        out = ticket["out"]
        width = ticket["width"]
        live_cols = live_tid = None
        if self._live_enabled and ticket.get("live_n"):
            from ..ops.edge_store import EDGE_COLS

            nlive = ticket["live_n"]
            live_cols = {
                c: np.asarray(out["live"][c])[:nlive] for c in EDGE_COLS
            }
            live_tid = np.asarray(out["live"]["tid"])[:nlive]
        if n_total:
            new = out["new"]
            hi = np.asarray(new["hi"])[:n_total]
            lo = np.asarray(new["lo"])[:n_total]
            ebits = np.asarray(new["ebits"])[:n_total]
            depth = np.asarray(new["depth"])[:n_total]
            tid = np.asarray(new["tid"])[:n_total]
            parent_hi = np.asarray(new["parent_hi"])[:n_total]
            parent_lo = np.asarray(new["parent_lo"])[:n_total]
            states = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[:n_total], new["states"]
            )
        deferred: Optional[TenantFaultError] = None
        for t in self.tenants():
            if t.faulted:
                # A flagged tenant's verdict slice is skipped: it is
                # rolled back to its pre-wave boundary either way, so
                # applying (or half-applying) this wave would only
                # corrupt the payload it leaves with.
                continue
            k = t.slot
            n_k = int(ticket["new_t"][k])
            survivors = 0
            stale = 0
            try:
                if live_cols is not None and not t.done:
                    # This tenant's slice of the wave's edge rows into
                    # its own partition — inside the per-tenant try, so
                    # an absorb fault (the liveness.edge_evict seam)
                    # lane-drops only this tenant (pack-local blast
                    # radius; absorbs are idempotent, so the rolled-back
                    # tenant's retry re-absorbing them is harmless).
                    lsel = np.flatnonzero(live_tid == k)
                    if len(lsel):
                        self._live_tenant_store(t).absorb(
                            **{
                                c: live_cols[c][lsel]
                                for c in live_cols
                            }
                        )
                if n_k and not t.done:
                    # Injection seam: one tenant's host-tier verdict
                    # slice dies (its probe, its numpy, its partition)
                    # — the pack-local blast-radius case. Fires before
                    # any of this tenant's state mutates, and the
                    # partition probe below carries the same per-tenant
                    # owner tag.
                    fault_point("pack.tenant.verdict", tenant=t.key)
                    sel = np.flatnonzero(tid == k)
                    child = fp64_pairs(hi[sel], lo[sel])
                    keep = np.arange(len(sel))
                    store = self._partitions.get(t.key)
                    if store is not None and not store.is_empty():
                        stale_mask = store.probe(child)
                        stale = int(stale_mask.sum())
                        keep = np.flatnonzero(~stale_mask)
                    survivors = len(keep)
                    if survivors:
                        kept = sel[keep]
                        child = child[keep]
                        parent = fp64_pairs(
                            parent_hi[kept], parent_lo[kept]
                        )
                        t.wave_log.append((child, parent))
                        t.resident.append(child)
                        t.unique_count += survivors
                        block = {
                            "states": jax.tree_util.tree_map(
                                lambda x: x[kept], states
                            ),
                            "hi": hi[kept],
                            "lo": lo[kept],
                            "ebits": ebits[kept],
                            "depth": depth[kept],
                        }
                        t.lanes.push(block, survivors)
                elif n_k and t.done:
                    # Discovery-complete tenants discard late fresh
                    # lanes (the solo loop would never have expanded
                    # them either way; their claims are table garbage
                    # like a dropped tenant's).
                    pass
            except BaseException as e:  # noqa: BLE001 - contained per tenant
                # Flag now (later attempts of this wave skip the
                # tenant) and defer the raise so every OTHER tenant's
                # slice of this verdict still applies — the whole point
                # of a pack-local blast radius.
                t.faulted = True
                t.fault_error = e
                if deferred is None:
                    deferred = TenantFaultError(t.key, e)
                    deferred.__cause__ = e
                continue
            lanes_k = ticket["lanes_by_slot"].get(k, 0)
            if lanes_k or n_k:
                if stale:
                    t.instruments.stale.inc(stale)
                t.instruments.record_wave(
                    lanes=lanes_k,
                    width=width,
                    generated=int(ticket["gen_t"][k]),
                    n_new=survivors,
                    pending=t.lanes.pending,
                    max_depth=t.max_depth,
                )
        if deferred is not None:
            raise deferred

    def _live_tenant_store(self, t: _Tenant):
        """The tenant's lazily-created liveness edge partition (its own
        store — per-tenant partitions mirror storage.TenantPartitions,
        and the owner tag routes the fault seam's tenant filter)."""
        if t.live_store is None:
            from ..storage import LivenessEdgeStore, LivenessInstruments

            t.live_store = LivenessEdgeStore(
                instruments=LivenessInstruments(
                    "pack", registry=t.registry
                ),
                owner=t.key,
            )
        return t.live_store

    def _tenant_liveness(self, t: _Tenant) -> None:
        """Finish-time per-tenant device-liveness verdict: the shared
        trim/reach pass over THIS tenant's edge partition (unsalted
        fps), so the packed verdict is exactly the solo run's."""
        from .device_liveness import analyze_liveness

        t.live_paths, t.live_outcomes = analyze_liveness(
            self._model,
            self._properties,
            self._ebit,
            self._live_tenant_store(t),
            self._host_fp,
            set(t.discoveries_fp),
            tracer=self._tracer,
        )
        self._tracer.instant(
            "pack.tenant_liveness", tenant=str(t.key),
            verdicts={
                k: v.get("verdict") for k, v in t.live_outcomes.items()
            },
        )

    def _ensure_capacity(self, incoming: int) -> None:
        need = self._l0 + incoming
        if need <= _MAX_LOAD * self._capacity:
            return
        self._grow(_pow2ceil(int(need / _MAX_LOAD)))

    def _finish_idle(self) -> List[object]:
        """Completes tenants with no pending lanes. The pre-scan below
        is only an optimization (skip the pipeline barrier while every
        tenant clearly has work); the DECIDING scan runs strictly AFTER
        the barrier. Checking ``pending()`` after snapshotting the
        candidates is the one intermittent bug this engine has shipped:
        a verdict completing in between pushes a tenant's survivors yet
        leaves a stale pending==0 snapshot, and with the pipe now idle
        the recheck never ran — the tenant finished with work still
        queued. After a barrier (or an observed-idle pipe), every push
        is visible, so the deciding scan is exact."""
        def scan():
            return [
                t
                for t in self.tenants()
                if not t.finished and not t.faulted
                and (t.done or t.lanes.pending == 0)
            ]

        if not scan():
            return []
        if self._pipe is not None and self._pipe.pending():
            self._pipe.drain()
        candidates = scan()
        if not candidates:
            return []
        finished = []
        for t in candidates:
            t.done = True
            if self._live_enabled and not t.faulted:
                # The tenant's exploration is complete: decide its
                # `eventually` verdicts before is_done() can observe
                # the finish (the service finalizes right after).
                self._tenant_liveness(t)
            t.finished = True
            t.lanes.clear()
            self._finish_view(t)
            finished.append(t.key)
            self._tracer.instant(
                "pack.tenant_done", tenant=str(t.key),
                unique=t.unique_count,
            )
        return finished

    def release(self, key) -> None:
        """Frees a COMPLETED tenant's slot (keep the view; its counters
        and parent store live on the view, not the slot)."""
        t = self._by_key.get(key)
        if t is None:
            return
        if not t.finished:
            raise RuntimeError(
                "release() is for completed tenants; use drop() to "
                "preempt a live one"
            )
        self._slots[t.slot] = None
        del self._by_key[t.key]
        self._partitions.drop(t.key)

    def close(self) -> None:
        if self._pipe is not None:
            try:
                self._pipe.drain()
            except Exception:  # noqa: BLE001 - poisoned: already surfaced
                pass
            finally:
                self._pipe.close()


def _pad_rows(x: np.ndarray, target: int) -> np.ndarray:
    n = len(x)
    if n == target:
        return x
    widths = [(0, target - n)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, widths)


def _concat_blocks(blocks, leaf_specs, treedef):
    """Dense concatenation of lane blocks (None when empty)."""
    if not blocks:
        return None
    out = {
        k: np.concatenate([b[k] for b in blocks])
        for k in ("hi", "lo", "ebits", "depth")
    }
    leaves = [
        np.concatenate(
            [jax.tree_util.tree_leaves(b["states"])[i] for b in blocks]
        )
        for i in range(len(leaf_specs))
    ]
    out["states"] = jax.tree_util.tree_unflatten(treedef, leaves)
    return out
