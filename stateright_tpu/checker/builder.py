"""Checker builder: configuration + spawn entry points for every backend.

Reference: ``CheckerBuilder`` at ``/root/reference/src/checker.rs:64-267``.
New in this framework: ``spawn_tpu_bfs`` (device frontier-expansion BFS) and
``spawn_tpu_simulation`` (vmapped random-walk lanes).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..core.visitor import CheckerVisitor, FnVisitor


def default_representative(state):
    """The ``symmetry()`` default. A named sentinel so device checkers can
    tell it apart from a user-supplied ``symmetry_fn`` (whose custom
    equivalence they cannot honor — they reduce by the full permutation
    group instead, which would over-merge under a partial symmetry)."""
    return state.representative()


class CheckerBuilder:
    def __init__(self, model):
        self.model = model
        self._symmetry: Optional[Callable] = None
        self._target_state_count: Optional[int] = None
        self._target_max_depth: Optional[int] = None
        self._thread_count: int = 1
        self._visitor: Optional[CheckerVisitor] = None
        self._complete_liveness: bool = False
        self._liveness_budget_states: Optional[int] = None
        self._liveness_deadline_s: Optional[float] = None

    # -- configuration -----------------------------------------------------

    def symmetry(self) -> "CheckerBuilder":
        """Enables symmetry reduction: host checkers dedup on
        ``state.representative()``; device checkers use orbit-proper
        minimum-fingerprint keys (see ``core/batch.py``)."""
        return self.symmetry_fn(default_representative)

    def symmetry_fn(self, representative: Callable) -> "CheckerBuilder":
        self._symmetry = representative
        return self

    def complete_liveness(self, budget_states: Optional[int] = None,
                          deadline_s: Optional[float] = None,
                          ) -> "CheckerBuilder":
        """Opt-in cycle-aware ``eventually`` checking (beyond the
        reference, whose semantics miss counterexamples that loop —
        documented FIXMEs at ``src/checker/bfs.rs:285-305``): after
        exploration, every undiscovered ``eventually`` property gets a
        host-side lasso search over the condition-false region
        (``checker/liveness.py``). Costs O(|condition-false region|) host
        time/memory, hence opt-in; the default semantics stay
        reference-exact. Honored by the exhaustive checkers
        (bfs/dfs/tpu_bfs/sharded_tpu_bfs), which refuse capped runs
        (``target_state_count``/``target_max_depth``) under this flag —
        the lasso search cannot honor caps.

        ``budget_states`` / ``deadline_s`` bound the pass: properties it
        cannot certify within the budget report an honest
        ``inconclusive`` outcome (reporter line, ``liveness.inconclusive``
        metric, ``liveness_report()``) instead of stalling
        ``discoveries()`` for unbounded host minutes. For sound verdicts
        WITHOUT the O(region) cost, prefer the device checkers'
        ``liveness="device"`` spawn knob (README "Trustworthy
        liveness")."""
        self._complete_liveness = True
        self._liveness_budget_states = budget_states
        self._liveness_deadline_s = deadline_s
        return self

    def target_state_count(self, count: int) -> "CheckerBuilder":
        """The checker may exceed this number, but will never generate fewer
        states if more exist."""
        self._target_state_count = count if count > 0 else None
        return self

    def target_max_depth(self, depth: int) -> "CheckerBuilder":
        self._target_max_depth = depth if depth > 0 else None
        return self

    def threads(self, thread_count: int) -> "CheckerBuilder":
        self._thread_count = thread_count
        return self

    def visitor(self, visitor) -> "CheckerBuilder":
        """A function or CheckerVisitor run on each evaluated state's path."""
        if not isinstance(visitor, CheckerVisitor):
            visitor = FnVisitor(visitor)
        self._visitor = visitor
        return self

    # -- spawns ------------------------------------------------------------

    def spawn_bfs(self):
        """Breadth-first host checker; shortest paths when single-threaded."""
        from .bfs import BfsChecker

        return BfsChecker(self)

    def spawn_dfs(self):
        """Depth-first host checker; dramatically less memory than BFS."""
        from .dfs import DfsChecker

        return DfsChecker(self)

    def spawn_on_demand(self):
        """Lazy checker that only computes states when asked (Explorer)."""
        from .on_demand import OnDemandChecker

        return OnDemandChecker(self)

    def spawn_simulation(self, seed: int, chooser=None):
        """Random-walk checking for state spaces too large to exhaust."""
        from .simulation import SimulationChecker, UniformChooser

        return SimulationChecker(self, seed, chooser or UniformChooser())

    def spawn_tpu_bfs(self, **kwargs):
        """TPU-accelerated BFS: vmapped frontier expansion + device-resident
        fingerprint set. Requires the model to implement ``BatchableModel``
        (or be convertible via ``stateright_tpu.models.packing``).
        ``wave_kernel="fused"`` runs the whole wave body — expand,
        fingerprint, sort-dedup, the VMEM tile-sweep insert, compaction,
        properties, coverage — as one Pallas dispatch per wave instead
        of the staged XLA chain (README "Fused wave megakernel");
        bit-identical to ``wave_kernel="staged"`` with
        ``wave_dedup="sort"``, interpreted off-TPU."""
        from .tpu import TpuBfsChecker

        return TpuBfsChecker(self, **kwargs)

    def spawn_sharded_tpu_bfs(self, mesh=None, **kwargs):
        """Multi-device BFS over a ``jax.sharding.Mesh``: the visited set is
        sharded by fingerprint range and candidate keys ride an all-to-all;
        states never leave the device that generated them."""
        from ..parallel.sharded import ShardedTpuBfsChecker

        return ShardedTpuBfsChecker(self, mesh=mesh, **kwargs)

    def spawn_tpu_simulation(self, seed: int, lanes: int = 1024, **kwargs):
        """TPU-accelerated simulation: N vmapped random-walk lanes."""
        from .tpu_simulation import TpuSimulationChecker

        return TpuSimulationChecker(self, seed, lanes, **kwargs)

    def spawn_swarm(self, seed: int, **kwargs):
        """Swarm verification: the entire randomized-walk loop runs
        device-resident — per-walk threefry PRNG streams, restart/
        boundary/depth/terminal handling, property evaluation, and
        discovery capture fused into one long jitted scan per wave —
        with a device hash-table sample of walk fingerprints for an
        honest unique-coverage estimate. For state spaces too large
        even for the tiered store; preemptible, packable, and
        seed-deterministic (README "Swarm verification"). Pass
        ``seeds=`` (a packed-state pool, or a budget-exhausted
        ``spawn_tpu_bfs`` preempt payload) for the frontier-seeded
        hybrid mode. Reference simulation semantics: the run ends when
        every property has a discovery or ``target_state_count`` is
        reached — a model with a HOLDING ``always`` property needs a
        walk-step target or it samples forever."""
        from .swarm import SwarmChecker

        return SwarmChecker(self, seed, **kwargs)

    def serve(self, address):
        """Starts the interactive Explorer web service (blocks)."""
        from .explorer import serve

        return serve(self, address)
