"""Device-native ``eventually`` soundness: edge logging + verdict + certificate.

The default device semantics reproduce the reference's documented false
negatives (``checker/liveness.py``): ``eventually`` bits merge at DAG
joins and cycles are invisible to a BFS that stores tree edges only. The
host post-pass fixes that at O(condition-false region) single-threaded
cost — minutes at raft-5 scale. ``liveness="device"`` replaces it with a
three-stage device-native procedure:

1. **Log** (in the wave jits, :func:`wave_edge_rows`): per eventually
   property, every (parent, child) transition whose BOTH endpoints fail
   the condition, plus condition-false terminal states and
   condition-false init states (roots). Appended to the capacity-budgeted
   device store (``ops/edge_store.py``), evicted to the host tier
   (``storage/edge_log.py``) when over budget.

2. **Decide** (:func:`analyze_liveness`): a counterexample exists iff the
   condition-false subgraph, restricted to states reachable from a
   condition-false init through condition-false states only, contains a
   cycle (lasso shape) or a terminal state (masked-terminal shape). The
   cycle half is the vmapped iterative-trim kernel (non-empty fixed
   point ⟺ a cycle exists among the logged edges); the restriction is
   the root-reachability kernel, run only when candidates exist — the
   absence verdict normally needs the trim alone, which is what makes
   absence certification cheap. Equivalence with the host pass
   (``find_eventually_lasso``): both decide "∃ maximal condition-false
   path from a condition-false init", whose finite-space shapes are
   exactly {reachable cycle, reachable terminal}.

3. **Certify**: a concrete :class:`~..core.path.Path` is extracted from
   the LOGGED edges — a deterministic BFS from the roots to the first
   candidate (shortest condition-false prefix), extended around the
   cycle by walking surviving successors when the candidate is a trim
   survivor — then replayed through the host model
   (``Path.from_fingerprints``), i.e. the existing host machinery seeded
   from the surviving fingerprint's state instead of searching from
   scratch.

Duplicate edges (table-growth retries re-expand a frontier) dedup in the
host store, so verdicts and certificates are independent of retry
timing, packing, and async pipelining — the bit-identity argument the
equivalence tests pin.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.model import Expectation
from ..core.path import Path

__all__ = [
    "wave_edge_rows",
    "seed_root_mask",
    "analyze_liveness",
    "LIVENESS_MODES",
]

# The spawn-knob vocabulary, shared by checkers and the service gate.
LIVENESS_MODES = (None, "default", "device")


def validate_liveness_mode(liveness, *, symmetry: bool, expand_fps,
                           options) -> Optional[str]:
    """Normalizes and validates the ``liveness=`` spawn knob for a
    device checker; returns ``"device"`` or ``None``. Raises on
    configurations whose edge relation would be incomplete (the verdict
    would silently lose soundness — refusing is the honest move)."""
    if liveness not in LIVENESS_MODES:
        raise ValueError(
            f"liveness must be one of {LIVENESS_MODES}, got {liveness!r}"
        )
    if liveness != "device":
        return None
    if symmetry:
        raise ValueError(
            "liveness='device' is incompatible with symmetry reduction: "
            "orbit-deduped states are never re-expanded, so the logged "
            "edge relation would miss their outgoing transitions and "
            "the cycle verdict would be unsound; use the host post-pass "
            "(.complete_liveness()) under symmetry"
        )
    if expand_fps:
        raise ValueError(
            "liveness='device' is incompatible with expand_fps=True: "
            "the fingerprint-only wave never materializes candidate "
            "states, so child condition values cannot be evaluated "
            "in-wave; drop expand_fps (device liveness forces the "
            "materializing wave)"
        )
    if (
        options._target_state_count is not None
        or options._target_max_depth is not None
    ):
        raise ValueError(
            "liveness='device' requires an uncapped run: a capped "
            "exploration logs a truncated edge relation, and a verdict "
            "over it could certify absence that a deeper run refutes"
        )
    return "device"


def wave_edge_rows(conditions, ebit: Dict[int, int], cond_vals, cand_flat,
                   cvalid_flat, terminal, hi, lo, chi, clo, A: int,
                   extra_lane=None, extra_row=None):
    """Traced inside a wave jit: the wave's condition-false edge and
    terminal rows, prefix-compacted into (B + F)-wide u32 columns
    (edges first, then terminal rows with the (0, 0) child sentinel).
    ``extra_lane``/``extra_row`` add per-lane (B-wide) / per-frontier-row
    (F-wide) int32 columns — the packed engine threads the tenant id
    through. Returns ``(rows, n)``."""
    B = cvalid_flat.shape[0]
    F = hi.shape[0]
    lanes = jnp.arange(B, dtype=jnp.int32)
    prow = lanes // A
    emask = jnp.zeros((B,), jnp.uint32)
    tmask = jnp.zeros((F,), jnp.uint32)
    for pi, b in ebit.items():
        pfalse = ~cond_vals[pi]
        cc = jax.vmap(conditions[pi])(cand_flat)
        ebit_lane = cvalid_flat & pfalse[prow] & ~cc
        emask = emask | jnp.where(
            ebit_lane, jnp.uint32(1 << b), jnp.uint32(0)
        )
        tbit = terminal & pfalse
        tmask = tmask | jnp.where(
            tbit, jnp.uint32(1 << b), jnp.uint32(0)
        )
    sel_e = emask != 0
    sel_t = tmask != 0
    n_e = sel_e.sum(dtype=jnp.int32)
    n_t = sel_t.sum(dtype=jnp.int32)
    width = B + F
    pos_e = jnp.cumsum(sel_e.astype(jnp.int32)) - 1
    pos_t = n_e + jnp.cumsum(sel_t.astype(jnp.int32)) - 1
    slot_e = jnp.where(sel_e, pos_e, width)
    slot_t = jnp.where(sel_t, pos_t, width)
    zu = jnp.zeros((width,), jnp.uint32)

    def scat(dst, idx, vals):
        return dst.at[idx].set(vals, mode="drop")

    rows = {
        "phi": scat(scat(zu, slot_e, hi[prow]), slot_t, hi),
        "plo": scat(scat(zu, slot_e, lo[prow]), slot_t, lo),
        "chi": scat(zu, slot_e, chi),
        "clo": scat(zu, slot_e, clo),
        "emask": scat(zu, slot_e, emask),
        "tmask": scat(zu, slot_t, tmask),
    }
    zi = jnp.zeros((width,), jnp.int32)
    for name, col in (extra_lane or {}).items():
        rows[name] = scat(zi, slot_e, col)
    for name, col in (extra_row or {}).items():
        rows[name] = scat(rows.get(name, zi), slot_t, col)
    return rows, n_e + n_t


def seed_root_mask(conditions, ebit: Dict[int, int], states, valid):
    """Traced in the seed jit: the per-init-lane u32 mask of eventually
    properties whose condition is FALSE at that (valid) init state —
    the analysis roots."""
    n0 = valid.shape[0]
    mask = jnp.zeros((n0,), jnp.uint32)
    for pi, b in ebit.items():
        false_here = valid & ~jax.vmap(conditions[pi])(states)
        mask = mask | jnp.where(
            false_here, jnp.uint32(1 << b), jnp.uint32(0)
        )
    return mask


# -- analysis ----------------------------------------------------------------


def _certificate_fps(src_idx, dst_idx, roots_idx, cand_mask, alive,
                     nodes) -> np.ndarray:
    """Deterministic certificate extraction over the logged edges:
    BFS (sorted adjacency, sorted root seed order) from the roots to the
    first candidate; a trim-surviving candidate is extended around its
    cycle by always walking the smallest surviving successor. Returns
    the fingerprint trail (u64)."""
    from collections import deque

    N = len(nodes)
    order = np.lexsort((dst_idx, src_idx))
    s_sorted = src_idx[order]
    d_sorted = dst_idx[order]
    starts = np.searchsorted(s_sorted, np.arange(N + 1))
    pred = np.full((N,), -1, np.int64)
    seen = np.zeros((N,), bool)
    q = deque()
    for r in sorted(roots_idx):
        if not seen[r]:
            seen[r] = True
            q.append(int(r))
    found = -1
    while q:
        v = q.popleft()
        if cand_mask[v]:
            found = v
            break
        for u in d_sorted[starts[v]:starts[v + 1]]:
            u = int(u)
            if not seen[u]:
                seen[u] = True
                pred[u] = v
                q.append(u)
    assert found >= 0, "certificate extraction: no candidate reachable"
    trail = [found]
    while pred[trail[-1]] >= 0:
        trail.append(int(pred[trail[-1]]))
    trail.reverse()
    if alive[found]:
        # Lasso: extend around the cycle — each survivor keeps at least
        # one surviving successor (the trim fixed-point invariant).
        on_walk = {found}
        cur = found
        while True:
            succs = d_sorted[starts[cur]:starts[cur + 1]]
            succs = [int(u) for u in succs if alive[u]]
            assert succs, "trim fixed point lost its successor"
            nxt = min(succs)
            trail.append(nxt)
            if nxt in on_walk:
                break
            on_walk.add(nxt)
            cur = nxt
    return nodes[np.asarray(trail, np.int64)]


def analyze_liveness(model, properties, ebit: Dict[int, int], store,
                     fp_of, have, instruments=None, tracer=None,
                     ) -> Tuple[Dict[str, Path], Dict[str, dict]]:
    """End-of-exploration device-liveness pass: one verdict per
    still-undiscovered ``eventually`` property. Returns
    ``(paths, outcomes)`` where ``outcomes[name]`` records the verdict
    (``"counterexample"`` / ``"absent"``) and the analysis evidence
    (edge/node counts, trim rounds, seconds)."""
    from ..ops.edge_store import lasso_trim, reach_any

    paths: Dict[str, Path] = {}
    outcomes: Dict[str, dict] = {}
    # One spill re-read + full-relation dedup for the whole pass: the
    # relation is property-independent; only the per-row mask bit
    # differs, and property_slice slices it from this shared view.
    all_rows = None
    for pi, prop in enumerate(properties):
        if prop.expectation != Expectation.EVENTUALLY:
            continue
        if prop.name in have:
            outcomes[prop.name] = {"verdict": "already_discovered"}
            continue
        b = ebit[pi]
        t0 = time.perf_counter()
        if all_rows is None:
            all_rows = store.edge_rows()
        src64, dst64, roots64, terms64 = store.property_slice(
            b, rows=all_rows
        )
        record = {
            "verdict": "absent",
            "edges": int(len(src64)),
            "roots": int(len(roots64)),
            "terminals": int(len(terms64)),
            "trim_rounds": 0,
            "survivors": 0,
        }
        if len(roots64) == 0:
            # Every init satisfies the condition already — every path
            # satisfies the property at step 0.
            record["seconds"] = time.perf_counter() - t0
            outcomes[prop.name] = record
            _count(instruments, record)
            continue
        nodes = np.unique(
            np.concatenate([roots64, terms64, src64, dst64])
        )
        N = len(nodes)
        src_idx = np.searchsorted(nodes, src64).astype(np.int32)
        dst_idx = np.searchsorted(nodes, dst64).astype(np.int32)
        evalid = np.ones((len(src_idx),), bool)
        nvalid = np.ones((N,), bool)
        record["nodes"] = N
        alive = np.zeros((N,), bool)
        if len(src_idx):
            alive, rounds = lasso_trim(src_idx, dst_idx, evalid, nvalid)
            record["trim_rounds"] = rounds
            record["survivors"] = int(alive.sum())
        term_mask = np.zeros((N,), bool)
        term_mask[np.searchsorted(nodes, terms64)] = True
        cand = alive | term_mask
        if cand.any():
            roots_idx = np.searchsorted(nodes, roots64)
            roots_mask = np.zeros((N,), bool)
            roots_mask[roots_idx] = True
            hit, _reach = reach_any(
                src_idx, dst_idx, evalid, roots_mask, cand
            )
            if hit:
                fps = _certificate_fps(
                    src_idx, dst_idx, roots_idx, cand, alive, nodes
                )
                paths[prop.name] = Path.from_fingerprints(
                    model, [int(f) for f in fps], fp_of=fp_of
                )
                record["verdict"] = "counterexample"
                record["certificate_len"] = int(len(fps))
        record["seconds"] = time.perf_counter() - t0
        outcomes[prop.name] = record
        _count(instruments, record)
        if tracer is not None:
            tracer.instant(
                "liveness.verdict", property=prop.name, **{
                    k: v for k, v in record.items() if k != "verdict"
                }, verdict=record["verdict"],
            )
    return paths, outcomes


def _count(instruments, record) -> None:
    if instruments is None:
        return
    instruments.trim_rounds.inc(record.get("trim_rounds", 0))
    if record["verdict"] == "counterexample":
        instruments.counterexamples.inc()
    elif record["verdict"] == "absent":
        instruments.absences.inc()
    if "seconds" in record:
        instruments.analysis_seconds.set(record["seconds"])
