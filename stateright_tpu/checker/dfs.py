"""Parallel depth-first host checker.

Jobs carry the entire fingerprint path, so discoveries store full paths (no
parent-pointer map needed, at the cost of O(depth) per job). Symmetry
reduction dedups on the representative's fingerprint while continuing the path
with the *original* state's fingerprint, keeping paths reconstructible.

Reference design: ``DfsChecker`` at ``/root/reference/src/checker/dfs.rs``
(including the symmetry path-continuation subtlety at ``:300-309``).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Set, Tuple

from ..core.fingerprint import Fingerprint, fingerprint
from ..core.model import Expectation
from ..core.path import Path
from ..telemetry import BlockInstruments, get_tracer
from ..telemetry.coverage import BlockCoverage, CoverageLedger
from .base import Checker
from .job_market import JobBroker

BLOCK_SIZE = 1500

# Job: (state, fingerprint-path, eventually-bits, depth)
Job = Tuple[object, List[Fingerprint], frozenset, int]


class DfsChecker(Checker):
    def __init__(self, options):
        model = options.model
        self._model = model
        symmetry = options._symmetry
        self._target_state_count: Optional[int] = options._target_state_count
        self._target_max_depth: Optional[int] = options._target_max_depth
        self._setup_lasso(options)
        thread_count = max(1, options._thread_count)
        visitor = options._visitor
        properties = model.properties()
        property_count = len(properties)

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        self._count_lock = threading.Lock()
        self._max_depth = 0
        self._generated: Set[Fingerprint] = set()
        for s in init_states:
            if symmetry is not None:
                self._generated.add(fingerprint(symmetry(s)))
            else:
                self._generated.add(fingerprint(s))
        ebits = frozenset(
            i
            for i, p in enumerate(properties)
            if p.expectation == Expectation.EVENTUALLY
        )
        pending: Deque[Job] = deque(
            (s, [fingerprint(s)], ebits, 1) for s in init_states
        )
        self._discoveries: Dict[str, List[Fingerprint]] = {}
        # Per-block telemetry (see the matching note in bfs.py).
        self._tracer = get_tracer()
        self._bi = BlockInstruments("dfs")
        # Always-on coverage ledger (see the matching note in bfs.py).
        self._cov = CoverageLedger(
            "dfs", properties, symmetry=symmetry is not None,
            tracer=self._tracer,
        )
        self._cov.record_seed(len(self._generated))
        self._job_broker: JobBroker[Job] = JobBroker(thread_count)
        self._job_broker.push(pending)
        self._worker_error: Optional[BaseException] = None
        self._handles: List[threading.Thread] = []
        self._symmetry = symmetry

        def worker(t: int):
            try:
                pending: Deque[Job] = deque()
                while True:
                    if not pending:
                        pending = self._job_broker.pop()
                        if not pending:
                            return
                    self._check_block(pending, properties, visitor)
                    if len(self._discoveries) == property_count:
                        return
                    if (
                        self._target_state_count is not None
                        and self._target_state_count <= self._state_count
                    ):
                        return
                    if len(pending) > 1 and thread_count > 1:
                        self._job_broker.split_and_push(pending)
            except BaseException as e:  # noqa: BLE001
                if self._worker_error is None:
                    self._worker_error = e
            finally:
                self._job_broker.close()
                self._finalize_coverage(set(self._discoveries))

        for t in range(thread_count):
            h = threading.Thread(
                target=worker, args=(t,), name=f"checker-{t}", daemon=True
            )
            h.start()
            self._handles.append(h)

    def _check_block(self, pending: Deque[Job], properties, visitor) -> None:
        model = self._model
        generated = self._generated
        discoveries = self._discoveries
        symmetry = self._symmetry
        max_count = BLOCK_SIZE
        actions: List = []
        # Accumulated locally and flushed under the lock once per block to keep
        # the hot loop off the lock (the reference uses relaxed atomics here).
        generated_count = 0
        block_max_depth = self._max_depth
        block_span = self._tracer.span("dfs.block")
        block_span.__enter__()
        bc = BlockCoverage(self._cov, model)
        try:
            while max_count > 0 and pending:
                max_count -= 1
                state, fingerprints, ebits, depth = pending.pop()

                if depth > block_max_depth:
                    block_max_depth = depth
                if (
                    self._target_max_depth is not None
                    and depth >= self._target_max_depth
                ):
                    continue
                bc.evaluated += 1
                if visitor is not None:
                    visitor.visit(
                        model, Path.from_fingerprints(model, fingerprints)
                    )

                is_awaiting_discoveries = False
                for i, prop in enumerate(properties):
                    if prop.name in discoveries:
                        continue
                    if prop.expectation == Expectation.ALWAYS:
                        if not prop.condition(model, state):
                            discoveries[prop.name] = list(fingerprints)
                        else:
                            is_awaiting_discoveries = True
                        ant = prop.antecedent
                        if ant is None or ant(model, state):
                            bc.exercise(i)
                    elif prop.expectation == Expectation.SOMETIMES:
                        if prop.condition(model, state):
                            discoveries[prop.name] = list(fingerprints)
                            bc.exercise(i)
                        else:
                            is_awaiting_discoveries = True
                    else:  # EVENTUALLY
                        is_awaiting_discoveries = True
                        if prop.condition(model, state):
                            ebits = ebits - {i}
                        if i not in ebits:
                            bc.exercise(i)
                if not is_awaiting_discoveries:
                    return

                is_terminal = True
                succ = 0
                actions.clear()
                model.actions(state, actions)
                for action in actions:
                    next_state = model.next_state(state, action)
                    if next_state is None:
                        continue
                    if not model.within_boundary(next_state):
                        continue
                    generated_count += 1
                    succ += 1
                    if symmetry is not None:
                        # Dedup on the canonical member of the equivalence
                        # class, but continue the path with the
                        # pre-canonicalized state's fingerprint so path replay
                        # stays valid.
                        representative_fp = fingerprint(symmetry(next_state))
                        if representative_fp in generated:
                            is_terminal = False
                            bc.action(action, False)
                            continue
                        generated.add(representative_fp)
                        next_fp = fingerprint(next_state)
                    else:
                        next_fp = fingerprint(next_state)
                        if next_fp in generated:
                            is_terminal = False
                            bc.action(action, False)
                            continue
                        generated.add(next_fp)
                    is_terminal = False
                    bc.action(action, True)
                    bc.depth[depth + 1] = bc.depth.get(depth + 1, 0) + 1
                    pending.append(
                        (next_state, fingerprints + [next_fp], ebits, depth + 1)
                    )
                bc.succ[succ] = bc.succ.get(succ, 0) + 1
                if is_terminal:
                    bc.terminals += 1
                    for i, prop in enumerate(properties):
                        # Insert-if-vacant: a stale ebit (clearing stops once
                        # the property is discovered) must not overwrite the
                        # valid counterexample — see the matching note in
                        # bfs.py; counts are unaffected.
                        if i in ebits and prop.name not in discoveries:
                            discoveries[prop.name] = list(fingerprints)
        finally:
            with self._count_lock:
                self._state_count += generated_count
                if block_max_depth > self._max_depth:
                    self._max_depth = block_max_depth
            self._bi.record(
                block_span,
                evaluated=BLOCK_SIZE - max_count,
                generated=generated_count,
                max_depth=block_max_depth,
                unique_total=len(generated),
                pending=len(pending),
            )
            bc.flush(max_depth=block_max_depth)

    # -- Checker surface ---------------------------------------------------

    def model(self):
        return self._model

    def state_count(self) -> int:
        # Block-local counters flush once per check_block; clamp so the
        # documented invariant state_count >= unique_state_count holds for
        # mid-run polls too.
        return max(self._state_count, len(self._generated))

    def unique_state_count(self) -> int:
        return len(self._generated)

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> Dict[str, Path]:
        out = {
            name: Path.from_fingerprints(self._model, fps)
            for name, fps in list(self._discoveries.items())
        }
        return self._with_lassos(
            out, self._job_broker.is_closed(), self._discoveries
        )

    def handles(self) -> List[threading.Thread]:
        handles, self._handles = self._handles, []
        return handles

    def is_done(self) -> bool:
        return self._job_broker.is_closed() or len(self._discoveries) == len(
            self._model.properties()
        )

    def worker_error(self) -> Optional[BaseException]:
        return self._worker_error
