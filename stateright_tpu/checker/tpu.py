"""TPU breadth-first checker: frontier waves expanded as fused device kernels.

This is the TPU-native re-architecture of the reference's ``BfsChecker``
(``/root/reference/src/checker/bfs.rs``). Where the reference runs N worker
threads popping 1500-state blocks from a ``JobBroker`` and deduplicating
through a concurrent ``DashMap``, this checker runs the whole search
inside one compiled device loop (the *deep drain*): a device-resident
FIFO ring holds the pending frontier, and each iteration runs one wave

    frontier batch ──vmap(packed_step over F×A grid)──▶ candidates
      ──fingerprint (u32-pair murmur fold)──▶ keys
      ──sort-dedup within wave──▶ wave-unique keys
      ──scatter-claim insert into device hash set──▶ fresh mask
      ──masked-cumsum compaction──▶ ring push + next frontier dequeue

exiting to the host only when the parent-fp log fills, the visited table
or ring needs growing, or an undiscovered property hit. At each exit the
host receives: scalar counters, per-property discovery fingerprints, and
the (child fp, parent fp) pairs needed for TLC-style path reconstruction
(Yu/Manolios/Lamport), which replays the *host* model along the
fingerprint trail exactly like the reference
(``/root/reference/src/checker/path.rs:20-97``). Wave-at-a-time mode
(``max_drain_waves=1``, or any visitor/target-count run) keeps the old
per-wave host loop for callback and overshoot granularity.

Semantics parity notes (all mirrored from the reference):
- ``eventually`` bits propagate along paths and are NOT part of the
  fingerprint, reproducing the documented false-negative on DAG joins and
  cycles (``/root/reference/src/checker/bfs.rs:285-305``).
- ``target_state_count``/``target_max_depth`` may overshoot by up to a wave
  (the reference overshoots by up to a block, ``src/checker.rs:234-236``).
- Symmetry reduction (``.symmetry()``) EXCEEDS the reference's BFS (which
  ignores it): visited keys become orbit-minimum fingerprints, re-avalanched
  for home-slot uniformity (see ``_make_key_fn``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.batch import BatchableModel
from ..core.model import Expectation
from ..core.path import Path
from ..native import make_fingerprint_store
from ..ops.fingerprint import (
    FP_SCHEME,
    avalanche32,
    fingerprint_state,
    fp64_pairs,
    fp_to_int,
)
from ..ops.hashset import (
    hashset_insert,
    hashset_insert_unsorted,
    hashset_new,
)
from ..ops.ring import ring_export, ring_push, ring_rows, ring_take
from ..telemetry import (
    WaveInstruments,
    device_step_annotation,
    get_tracer,
    metrics_registry,
)
from ..utils.faults import fault_point
from .base import _NULL_CTX, Checker  # noqa: F401 - _NULL_CTX re-exported
from .pipeline import HostPipeline

_DEPTH_INF = (1 << 31) - 1
_U32_MAX = np.uint32(0xFFFFFFFF)  # numpy: keeps module import backend-free
# Grow the device hash set before load factor can exceed this.
_MAX_LOAD = 0.55
# Smallest bucket in the occupancy-adaptive wave ladder: one packed tile
# (8 sublanes) — narrower dispatches are dominated by fixed launch cost.
_MIN_BUCKET = 8
# Default ladder depth: F_max/16 … F_max (4 power-of-two halvings).
_DEFAULT_BUCKET_STEPS = 4
# The ladder auto-engages (bucket_ladder=None) only at this frontier
# capacity or above: below it a full wave is already microseconds of
# masked waste, so the rung compiles could never pay for themselves.
# Pass bucket_ladder explicitly to force either way.
_AUTO_BUCKET_MIN_F = 512


def bucket_ladder_widths(f_max: int, steps: int) -> list:
    """The descending power-of-two wave-width ladder for a checker with
    frontier capacity ``f_max``: ``[F_max, F_max/2, …]`` down to
    ``max(F_max >> steps, _MIN_BUCKET)``. ``steps=0`` disables bucketing
    (a single fixed-width rung). Shared by the checkers and the
    breakdown mirror so the measured ladder is the dispatched ladder."""
    floor = max(min(f_max, _MIN_BUCKET), f_max >> max(0, steps))
    return [f_max >> i for i in range(steps + 1) if (f_max >> i) >= floor]


def bucket_for(widths, live: int) -> int:
    """The smallest ladder width that holds ``live`` lanes (``widths``
    descending; the widest rung is returned when nothing smaller fits)."""
    chosen = widths[0]
    for w in widths[1:]:
        if live <= w:
            chosen = w
    return chosen


def packed_model_digest(model, action_count: int) -> str:
    """Digest of a model's packed configuration, guarding checkpoint resume:
    the class-name check alone would let e.g. a 3-RM checkpoint resume a
    4-RM model."""
    from hashlib import blake2b

    h = blake2b(digest_size=16)
    h.update(type(model).__name__.encode())
    h.update(str(action_count).encode())
    for leaf in jax.tree_util.tree_leaves(model.packed_init_states()):
        arr = np.asarray(leaf)
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def checkpoint_header(
    kind: str, model, action_count: int, symmetry: bool, sym_scheme=None
) -> dict:
    """Common checkpoint header shared by every device checker.
    ``sym_scheme`` is the visited-key scheme tag (``sym_key_scheme``);
    legacy callers passing only the bool get the group scheme."""
    if symmetry and sym_scheme is None:
        sym_scheme = SYM_KEY_SCHEME
    return {
        # v2 (out-of-core tiering): adds the optional "storage" payload
        # (L1/L2 fingerprint runs + Bloom filters). v3 (device
        # liveness): adds the optional "liveness" payload (the
        # condition-false edge store + roots/terminals) — writers stamp
        # 3 only when that payload is present, so v2 readers keep
        # restoring every checkpoint written without liveness="device".
        # v1/v2 checkpoints still restore; see MIGRATING.md.
        "version": 2,
        "kind": kind,
        "model": type(model).__name__,
        "model_digest": packed_model_digest(model, action_count),
        "symmetry": symmetry,
        "sym_scheme": sym_scheme if symmetry else None,
        "fp_scheme": FP_SCHEME,
    }


def validate_checkpoint_header(
    payload: dict,
    kind: str,
    wrong_kind_hint: str,
    model,
    action_count: int,
    symmetry: bool,
    sym_scheme=None,
) -> None:
    """Rejects checkpoints another checker kind, model, model configuration,
    or symmetry setting wrote. Checkpoints predating the ``kind`` field were
    written by the single-device checker (the only kind that existed)."""
    if payload.get("version") not in (1, 2, 3):
        raise ValueError(f"unsupported checkpoint version: {payload!r}")
    found_kind = payload.get("kind", "tpu_bfs")
    if found_kind != kind:
        raise ValueError(
            f"checkpoint kind {found_kind!r} does not match this checker "
            f"({kind!r}): {wrong_kind_hint}"
        )
    if payload["model"] != type(model).__name__:
        raise ValueError(
            f"checkpoint was written by model {payload['model']!r}, "
            f"resuming with {type(model).__name__!r}"
        )
    if payload.get("model_digest") != packed_model_digest(model, action_count):
        raise ValueError(
            "checkpoint was written by a differently-configured model "
            "(packed init states / action count do not match); resuming "
            "would mix two state spaces"
        )
    if payload.get("symmetry", False) != symmetry:
        raise ValueError(
            "checkpoint symmetry setting does not match this checker "
            "(visited keys are canonical-form fingerprints under symmetry, "
            "plain fingerprints otherwise; the two key spaces cannot mix)"
        )
    if symmetry:
        want = sym_scheme if sym_scheme is not None else SYM_KEY_SCHEME
        if payload.get("sym_scheme") != want:
            raise ValueError(
                f"checkpoint symmetry-key scheme "
                f"{payload.get('sym_scheme')!r} does not match this "
                f"checker ({want!r}); its visited keys cannot be mixed "
                "into a resumed run"
            )
    if payload.get("fp_scheme") != FP_SCHEME:
        raise ValueError(
            f"checkpoint fingerprint scheme {payload.get('fp_scheme')!r} "
            f"does not match this build ({FP_SCHEME!r}); its visited keys "
            "and parent fps cannot be mixed into a resumed run"
        )


def atomic_pickle(path, payload) -> None:
    """Writes the pickle to ``path`` atomically (tmp file + rename), so a
    kill mid-checkpoint never corrupts the previous checkpoint."""
    import os
    import pickle

    # Injection seam: a real checkpoint write fails on ENOSPC, a torn
    # NFS rename, or fs remount — always BEFORE the rename, so the
    # previous checkpoint survives the fault (the atomicity guarantee
    # this function exists for).
    fault_point("checkpoint.write")
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f)
    os.replace(tmp, path)


# Symmetry visited-key scheme. r2 keyed on the n!-loop orbit-minimum
# fingerprint; r3 keys verified lanes on the canonical-permutation
# fingerprint (WL refinement) with per-lane orbit-minimum fallback — a
# different (still orbit-proper) key space, so symmetry checkpoints
# record and validate this tag.
SYM_KEY_SCHEME = "wl-canon+orbitmin-v2"
# Custom ``symmetry_fn`` runs key on fp(model.packed_representative(s)) —
# a third key space, tagged separately in checkpoints.
CUSTOM_REP_SCHEME = "custom-representative-v1"


def sym_key_scheme(symmetry) -> "Optional[str]":
    """The visited-key scheme tag a symmetry setting implies (None when
    symmetry is off) — recorded in checkpoints so runs never resume across
    incompatible key spaces."""
    if symmetry is None:
        return None
    from .builder import default_representative

    return (
        SYM_KEY_SCHEME
        if symmetry is default_representative
        else CUSTOM_REP_SCHEME
    )


def _make_key_fn(model, fp_fn, symmetry):
    """Batched dedup-key function for the device checkers, or ``None`` when
    symmetry is off (callers then use the plain fingerprints they already
    computed).

    Under symmetry the key is a canonical-form fingerprint — a true orbit
    invariant, so dedup merges states iff they share an orbit. Two routes
    compute it:

    - **Refined (fast)**: when the model implements
      ``packed_refine_colors`` (see ``core/batch.py``), iterate the
      WL-style equivariant color refinement, sort actors by final color
      (candidate canonical permutation), and VERIFY remaining color ties
      are automorphisms by checking each adjacent tied transposition
      leaves the fingerprint unchanged (adjacent transpositions generate
      each tie class's full symmetric group). Verified lanes key on the
      canonical-permutation fingerprint: ~``n`` fingerprint passes per
      state.
    - **Orbit-minimum (exact fallback)**: a sequential ``fori_loop`` over
      all ``n!`` permutations taking the minimum fingerprint — vmapping
      the group axis instead would materialize ``B x n!`` permuted states
      at once. Used for the whole batch when the model has no refine
      hook, and selected per-lane (via ``lax.cond``, so the loop only
      executes on waves that need it) for lanes whose verification
      failed.

    The mix is consistent across waves: verification outcomes are orbit
    invariants (computed on the canonical state), so every member of an
    orbit takes the same route and thus the same key.
    """
    if symmetry is None:
        return None
    from .builder import default_representative

    if symmetry is not default_representative:
        from ..core.batch import BatchableModel

        has_rep = (
            type(model).packed_representative
            is not BatchableModel.packed_representative
        )
        if not has_rep:
            raise ValueError(
                "device checkers cannot honor a custom symmetry_fn unless "
                "the model implements packed_representative(): the built-in "
                "keys reduce by the FULL actor-permutation group, which "
                "would over-merge states under a partial symmetry. "
                "Implement packed_representative (core/batch.py), use "
                ".symmetry(), or a host checker."
            )

        def rep_keys(states_batch):
            # Plain fingerprints of the user's canonical form — they
            # inherit fingerprint_words' sentinel nudges, so no finalize.
            return jax.vmap(
                lambda s: fp_fn(model.packed_representative(s))
            )(states_batch)

        return rep_keys
    try:
        n2o, o2n = model.packed_symmetry()
    except (AttributeError, NotImplementedError) as e:
        raise TypeError(
            "symmetry on the device path requires the model to implement "
            "packed_symmetry()/packed_apply_permutation() (see "
            "stateright_tpu.core.batch)"
        ) from e
    n2o = jnp.asarray(n2o)
    o2n = jnp.asarray(o2n)
    n_perms, n = n2o.shape

    def full_min(states_batch):
        leaves = jax.tree_util.tree_leaves(states_batch)
        b = leaves[0].shape[0]

        def body(k, acc):
            mhi, mlo = acc
            his, los = jax.vmap(
                lambda s: fp_fn(
                    model.packed_apply_permutation(s, n2o[k], o2n[k])
                )
            )(states_batch)
            better = (his < mhi) | ((his == mhi) & (los < mlo))
            return jnp.where(better, his, mhi), jnp.where(better, los, mlo)

        full = jnp.full((b,), _U32_MAX)
        return jax.lax.fori_loop(0, n_perms, body, (full, full))

    def finalize(khi, klo):
        # Re-avalanche the keys: an orbit minimum over |G| uniform draws
        # concentrates in the low 1/|G| of the key space, which would pile
        # every home slot (top bits of hi — ops/hashset._home) into the
        # first capacity/|G| rows. The murmur finalizer is a bijection on
        # u32, so scrambling each word introduces no new collisions;
        # sentinels are nudged exactly like ops/fingerprint
        # .fingerprint_words. Canonical-permutation keys share the
        # finalizer so both routes draw from one key space.
        khi = avalanche32(khi ^ jnp.uint32(0x51A7CC9E))
        klo = avalanche32(klo ^ jnp.uint32(0xE3779B97))
        zero = (khi == 0) & (klo == 0)
        klo = jnp.where(zero, jnp.uint32(1), klo)
        maxed = (khi == _U32_MAX) & (klo == _U32_MAX)
        klo = jnp.where(maxed, jnp.uint32(_U32_MAX - 1), klo)
        return khi, klo

    from ..core.batch import BatchableModel

    has_refine = (
        type(model).packed_refine_colors
        is not BatchableModel.packed_refine_colors
    )
    if not has_refine:
        def orbit_keys(states_batch):
            return finalize(*full_min(states_batch))

        return orbit_keys

    # WL color partitions on n actors stabilize within n-1 rounds; extra
    # rounds only re-hash a stable partition.
    rounds = max(1, min(n - 1, 6))
    iota = jnp.arange(n, dtype=jnp.int32)
    # Adjacent-transposition index table, row i = identity with (i, i+1)
    # swapped. Both this loop and the refine loop run as fori_loops, not
    # unrolled — the key fn is traced inside every checker's wave/drain,
    # and an n-times-smaller HLO is real compile-warmup savings on the
    # slow-compile device tunnel (semantics are iteration-identical).
    swap_rows = np.tile(np.arange(n, dtype=np.int32), (max(n - 1, 1), 1))
    for i in range(n - 1):
        swap_rows[i, i], swap_rows[i, i + 1] = i + 1, i
    swap_tab = jnp.asarray(swap_rows)

    def refined_keys(states_batch):
        def one(s):
            colors = jax.lax.fori_loop(
                0,
                rounds,
                lambda _i, c: model.packed_refine_colors(s, c),
                jnp.zeros((n,), jnp.uint32),
            )
            sorted_colors, cand = jax.lax.sort(
                (colors, iota), num_keys=1
            )
            inv = jnp.zeros((n,), jnp.int32).at[cand].set(iota)
            hi0, lo0 = fp_fn(model.packed_apply_permutation(s, cand, inv))

            def check(i, ok):
                tie = sorted_colors[i] == sorted_colors[i + 1]
                cand_i = cand[swap_tab[i]]
                inv_i = jnp.zeros((n,), jnp.int32).at[cand_i].set(iota)
                hi_i, lo_i = fp_fn(
                    model.packed_apply_permutation(s, cand_i, inv_i)
                )
                return ok & (~tie | ((hi_i == hi0) & (lo_i == lo0)))

            ok = jax.lax.fori_loop(0, n - 1, check, jnp.bool_(True))
            return hi0, lo0, ok

        khi, klo, ok = jax.vmap(one)(states_batch)
        fhi, flo = jax.lax.cond(
            ok.all(), lambda: (khi, klo), lambda: full_min(states_batch)
        )
        return finalize(
            jnp.where(ok, khi, fhi), jnp.where(ok, klo, flo)
        )

    return refined_keys


def supports_expand_fps(model) -> bool:
    """Whether the model provides the fingerprint-only expansion hooks
    (``packed_expand_fps`` + ``packed_take``) AND allows them — THE
    definition shared by the checker's auto policy and bench.py's
    measured-policy calibration, so they cannot disagree about which
    pipelines exist for a model."""
    return (
        type(model).packed_expand_fps is not BatchableModel.packed_expand_fps
        and type(model).packed_take is not BatchableModel.packed_take
        and model.packed_expand_fps_supported()
    )


def default_wave_dedup(platform: str, hashset_impl: str = "xla") -> str:
    """THE definition of the backend wave-dedup default, shared by
    ``TpuBfsChecker``, ``measure_wave_breakdown``, and ``bench.py``:
    "scatter" on the CPU backend (the duplicate-tolerant unsorted insert
    measured 2.3x on 2pc-7 — XLA's single-threaded sort dominates wide
    waves there), "sort" elsewhere (sequential probe pattern, pending
    the on-chip A/B) and always under the Pallas insert kernel (it
    requires sorted batches)."""
    if hashset_impl == "pallas" or platform != "cpu":
        return "sort"
    return "scatter"


def _pow2ceil(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def min_admissible_hbm_budget_mib(model, frontier_capacity: int) -> float:
    """The smallest ``hbm_budget_mib`` a checker with this model and
    frontier width accepts — i.e. MAXIMUM eviction pressure. THE shared
    definition (the inverse of ``storage.max_table_rows_for_budget``,
    priced with the same 8-byte row + probe apron): one worst-case wave
    (frontier × action_count candidates) must fit a freshly-evicted
    table under ``_MAX_LOAD``. bench.py's --async-ab leg and the
    equivalence tests both use it, so a load-factor or layout change
    cannot silently stop their budgets from binding."""
    from ..ops.hashset import MAX_PROBES

    rows = _pow2ceil(
        int(
            _pow2ceil(frontier_capacity)
            * model.packed_action_count()
            / _MAX_LOAD
        )
        + 1
    )
    return ((rows + MAX_PROBES) * 8) / (1 << 20)


# -- cross-checker AOT executable sharing (checking-as-a-service) -----------
#
# One resident process serving many jobs must never recompile a wave shape
# a previous job already built: the wave/drain executables are pure XLA
# programs (model constants baked in at trace time), so two checker
# INSTANCES whose traces are provably identical can share them. "Provably"
# is the caller's namespace (e.g. the service's model-zoo entry name)
# ANDed with a full trace signature — model digest, property list, key
# scheme, pipeline, ladder, capacities — so a namespace collision between
# genuinely different configurations still misses instead of corrupting.
_AOT_LOCK = threading.Lock()
_AOT_CACHES: Dict[tuple, dict] = {}


def shared_aot_cache(namespace: str, signature: tuple) -> dict:
    """The process-global executable dict for one (namespace, signature)
    — get-or-create, so every checker spawned with the same
    ``aot_cache=namespace`` and an identical trace signature probes and
    populates the same cache."""
    key = (namespace, signature)
    with _AOT_LOCK:
        return _AOT_CACHES.setdefault(key, {})


def clear_shared_aot_caches() -> None:
    """Drops every shared executable (tests / memory reclamation)."""
    with _AOT_LOCK:
        _AOT_CACHES.clear()


class TpuBfsChecker(Checker):
    """Requires the model to implement ``BatchableModel``.

    ``frontier_capacity`` caps lanes per wave (larger frontiers split into
    chunks); ``table_capacity`` is the initial device hash-set size (grows
    by doubling + rehash). ``bucket_ladder`` is the occupancy-adaptive
    dispatch depth: the number of power-of-two bucket widths below
    ``F_max`` a wave may dispatch at (None auto-selects 4 →
    ``F_max/16 … F_max`` when ``F_max >= 512`` and fixed width below
    that, where rung compiles cannot pay for themselves; 0 forces fixed
    width); see README "Performance tuning".

    ``hbm_budget_mib`` enables out-of-core mode: the device table is
    hard-capped at the budget, growth past it evicts the full table to
    host-resident delta-compressed runs (L1), and ``host_budget_mib`` /
    ``spill_dir`` spill merged runs to disk (L2). Results are
    bit-identical to the unbounded run; see README "Memory hierarchy".

    ``async_pipeline=True`` turns the wave loop into a two-deep
    pipeline: wave N+1's expand/fingerprint/insert runs on device while
    a host worker thread applies wave N's tiered-store probe, eviction
    absorbs, and checkpoint serialization; survivors of the deferred
    probe re-enter the frontier one wave late at the queue tail —
    exactly where the synchronous path would have appended them — so
    results stay bit-identical (README "Async pipeline"). Requires no
    visitor (per-chunk callbacks need each wave's verdict before the
    next dispatch).
    """

    def __init__(
        self,
        options,
        frontier_capacity=1 << 13,
        table_capacity=1 << 16,
        checkpoint_path=None,
        checkpoint_every_chunks=32,
        checkpoint_min_interval_s=0.0,
        resume_from=None,
        profile_dir=None,
        max_drain_waves=100_000,
        drain_log_factor=8,
        pool_factor=16,
        hashset_impl="xla",
        wave_dedup=None,
        expand_fps=None,
        bucket_ladder=None,
        hbm_budget_mib=None,
        host_budget_mib=None,
        spill_dir=None,
        attribution=False,
        coverage=False,
        run_id=None,
        aot_cache=None,
        aot_store=None,
        async_pipeline=False,
        liveness=None,
        edge_log_capacity=None,
        wave_kernel="staged",
        config_notes=None,
    ):
        model = options.model
        if not isinstance(model, BatchableModel):
            raise TypeError(
                f"spawn_tpu_bfs requires a BatchableModel; {type(model).__name__} "
                "does not implement the packed protocol (see stateright_tpu.core.batch)"
            )
        self._model = model
        self._properties = model.properties()
        # Run identity (checking-as-a-service): ``run_id=`` gives this
        # checker its own metrics registry (no instrument collisions
        # between concurrent runs in one process) and stamps every trace
        # span with the id so monitors can select this run's stream.
        self.run_id = run_id
        self._registry = metrics_registry(run_id) if run_id else None
        self._tracer = get_tracer(run_id)
        self._conditions = model.packed_conditions()
        if len(self._conditions) != len(self._properties):
            raise ValueError(
                "packed_conditions() must align 1:1 with properties(): "
                f"{len(self._conditions)} != {len(self._properties)}"
            )
        eventually = [
            i
            for i, p in enumerate(self._properties)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if len(eventually) > 32:
            raise ValueError("at most 32 eventually properties supported")
        self._ebit: Dict[int, int] = {pi: b for b, pi in enumerate(eventually)}
        self._ebits0 = sum(1 << b for b in self._ebit.values())
        self._A = model.packed_action_count()
        # Waves dispatch on a power-of-two bucket ladder (F_max down to
        # F_max >> bucket_ladder): each chunk runs at the smallest bucket
        # holding its live lanes, so wave cost scales with occupancy
        # instead of capacity. Every rung compiles once per table shape
        # (the AOT cache below is keyed on (bucket, table_capacity)), so
        # steady state never recompiles — recompilation through the
        # device tunnel costs tens of seconds per shape.
        # ``bucket_ladder``: number of halvings below F_max (None = the
        # default ladder, 0 = fixed-width dispatch).
        self._F_max = _pow2ceil(frontier_capacity)
        if bucket_ladder is None:
            bucket_ladder = (
                _DEFAULT_BUCKET_STEPS
                if self._F_max >= _AUTO_BUCKET_MIN_F
                else 0
            )
        if bucket_ladder < 0:
            raise ValueError(
                f"bucket_ladder must be >= 0, got {bucket_ladder}"
            )
        self._buckets = bucket_ladder_widths(self._F_max, bucket_ladder)
        self._capacity = table_capacity
        # Out-of-core tiering (stateright_tpu.storage): ``hbm_budget_mib``
        # hard-caps the device hash table. Growth past the cap drains the
        # full table to host L1 runs instead of doubling (``_evict_l0``),
        # and every later wave's L0-fresh lanes batch-probe L1/L2 at the
        # wave's host exit — membership is the union of the tiers, so
        # results stay bit-identical to the unbounded single-tier path
        # (tests/test_storage_equivalence.py). See README "Memory
        # hierarchy".
        from ..storage import (
            TieredVisitedStore,
            max_table_rows_for_budget,
            validate_budget_knobs,
        )

        validate_budget_knobs(hbm_budget_mib, host_budget_mib, spill_dir)
        self._tier = None
        self._max_capacity = None
        if hbm_budget_mib is not None:
            max_cap = max_table_rows_for_budget(hbm_budget_mib)
            # A freshly-evicted (empty) table must absorb one worst-case
            # wave (F_max × A candidates) under the load cap, or the
            # grow-and-retry loop could never terminate.
            min_cap = _pow2ceil(
                int(self._F_max * self._A / _MAX_LOAD) + 1
            )
            if max_cap < min_cap:
                raise ValueError(
                    f"hbm_budget_mib={hbm_budget_mib} allows a device "
                    f"table of {max_cap} rows, but one worst-case wave "
                    f"(frontier_capacity × action_count = "
                    f"{self._F_max * self._A} candidates) needs at least "
                    f"{min_cap}; raise the budget or shrink "
                    "frontier_capacity"
                )
            self._max_capacity = max_cap
            self._capacity = min(self._capacity, max_cap)
            from ..storage import StorageInstruments

            self._tier = TieredVisitedStore(
                host_budget_mib=host_budget_mib,
                spill_dir=spill_dir,
                instruments=StorageInstruments(
                    "tpu_bfs", registry=self._registry
                ),
                tracer=self._tracer,
            )
        # Keys currently RESIDENT in the device table (== unique_count
        # until the first eviction; afterwards the table holds only the
        # working set plus re-claimed hot keys).
        self._l0_count = 0
        # Visited-set insert kernel for the sorted wave batches: "xla"
        # (gather/scatter probing, ops/hashset.py) or "pallas" (tile-sweep
        # DMA kernel, ops/pallas_hashset.py — measure both with
        # ``python -m stateright_tpu.ops.bench_hashset`` and pick the
        # winner per backend). The unsorted sites (_rehash, checkpoint
        # restore) always use the XLA path.
        if hashset_impl not in ("xla", "pallas"):
            raise ValueError(
                f"hashset_impl must be 'xla' or 'pallas', got {hashset_impl!r}"
            )
        self._hashset_impl = hashset_impl
        if wave_kernel not in ("staged", "fused"):
            raise ValueError(
                f"wave_kernel must be 'staged' or 'fused', got "
                f"{wave_kernel!r}"
            )
        self._wave_kernel = wave_kernel
        # Run-configuration notes, surfaced once at run end through
        # ``Reporter.report_config_notes`` — a silently adjusted knob is
        # a dishonest one. Callers (the service's warm-start plane) may
        # pre-seed notes of their own.
        self.config_notes: List[str] = list(config_notes or ())
        if wave_kernel == "fused":
            # The fused wave grids over TILE_ROWS-row table tiles; round
            # the capacity up to the next admissible size (and say so)
            # instead of refusing admission. The staged pallas insert
            # below keeps its hard refusal: rounding there would change
            # the documented contract of an existing knob.
            from ..ops.pallas_hashset import TILE_ROWS, round_table_capacity

            rounded = round_table_capacity(self._capacity)
            if rounded != self._capacity:
                if (
                    self._max_capacity is not None
                    and rounded > self._max_capacity
                ):
                    raise ValueError(
                        f"table_capacity={self._capacity} rounds up to "
                        f"{rounded} rows for the tile-sweep kernels "
                        f"({TILE_ROWS}-row tiles), which exceeds the "
                        f"hbm_budget_mib cap of {self._max_capacity} rows; "
                        "raise the budget or shrink table_capacity"
                    )
                self.config_notes.append(
                    f"table_capacity rounded {self._capacity} -> {rounded} "
                    f"(tile-sweep kernels grid over {TILE_ROWS}-row table "
                    "tiles)"
                )
                self._capacity = rounded
        elif hashset_impl == "pallas":
            from ..ops.pallas_hashset import TILE_ROWS

            if self._capacity % TILE_ROWS:
                raise ValueError(
                    "hashset_impl='pallas' needs table_capacity to be a "
                    f"multiple of {TILE_ROWS} (got {self._capacity})"
                )
        # In-wave dedup strategy; None = the shared backend default
        # (``default_wave_dedup``). The fused wave embeds the sort-dedup
        # in its prologue, so its default is always "sort".
        if wave_dedup is None:
            wave_dedup = (
                "sort"
                if wave_kernel == "fused"
                else default_wave_dedup(jax.default_backend(), hashset_impl)
            )
        if wave_dedup not in ("sort", "scatter"):
            raise ValueError(
                f"wave_dedup must be 'sort' or 'scatter', got {wave_dedup!r}"
            )
        if wave_dedup == "scatter" and (
            hashset_impl == "pallas" or wave_kernel == "fused"
        ):
            raise ValueError(
                "wave_dedup='scatter' is incompatible with the tile-sweep "
                "Pallas kernels (hashset_impl='pallas' and "
                "wave_kernel='fused' both require sorted batches); drop "
                "the scatter override or select wave_kernel='staged' with "
                "hashset_impl='xla'"
            )
        self._wave_dedup = wave_dedup
        self._visitor = options._visitor
        self._target_state_count: Optional[int] = options._target_state_count
        self._depth_cap = options._target_max_depth or _DEPTH_INF
        self._setup_lasso(options)

        self._checkpoint_path = checkpoint_path
        # Counts dequeued frontier chunks (a wide BFS level splits into many
        # F_max-sized chunks); the time floor keeps wide frontiers from
        # checkpointing (full parent-map export + pickle) back to back.
        self._checkpoint_every = max(1, checkpoint_every_chunks)
        self._checkpoint_min_interval = checkpoint_min_interval_s
        self._resume_from = resume_from
        # SURVEY §5: per-frontier-wave profiler hooks. When set, the run is
        # wrapped in a JAX profiler trace (viewable in TensorBoard /
        # Perfetto) and every wave gets a StepTraceAnnotation.
        self._profile_dir = profile_dir
        # Deep device drain: the BFS runs inside one lax.while_loop with a
        # device-resident FIFO ring of pending states (the "pool"), exiting
        # to the host only to drain the parent-fp log, grow the table,
        # record a property discovery, or spill a pool overflow. Each host
        # round trip through a device tunnel costs ~0.1-1s; amortizing it
        # over thousands of waves is what makes the device path win
        # (SURVEY §7-5c's host-loop concern). 1 disables (wave-at-a-time);
        # also disabled when a visitor needs per-chunk callbacks or a
        # target count caps the run (overshoot would span whole drains).
        self._max_drain_waves = max(1, max_drain_waves)
        if checkpoint_path is not None:
            # A deep drain can span the whole run, which would starve the
            # periodic checkpointer; durability caps waves-per-drain so a
            # checkpoint opportunity arises at least every N waves. The
            # floor of 2 keeps the deep path selected (1 means "disabled").
            self._max_drain_waves = min(
                self._max_drain_waves, max(2, checkpoint_every_chunks)
            )
        # Log must hold at least one worst-case wave (F·A fresh states) or
        # such a wave could never be consumed device-side.
        self._drain_log_capacity = max(
            max(1, drain_log_factor) * self._F_max, self._F_max * self._A
        )
        # Pool ring capacity (power of two, ≥ one worst-case wave output).
        self._pool_capacity = _pow2ceil(
            max(max(1, pool_factor) * self._F_max, self._F_max * self._A)
        )

        self._state_count = 0
        self._unique_count = 0
        self._max_depth = 0
        self._discoveries_fp: Dict[str, int] = {}
        # (child fps u64, parent fps u64 — 0 encodes "init state") per wave,
        # ingested into the native parent-pointer store (C++ open-addressing
        # map; see stateright_tpu.native) for path reconstruction.
        self._wave_log: List = []
        # Under symmetry: the u64 visited-set keys claimed so far (the
        # checkpoint needs them — the table cannot be rebuilt from the
        # original fps in the parent store).
        self._key_log: List = []
        self._store = make_fingerprint_store()
        # Telemetry: instruments resolved once; the wave/drain loops emit
        # one span per wave (frontier width, new-unique, dedup hit-rate,
        # hash-set occupancy, max depth) through them — the live
        # observability the offline breakdown.py stage mirror cannot give.
        # (Tracer/registry already bound above — run_id-scoped when set.)
        self._wi = WaveInstruments("tpu_bfs", registry=self._registry)
        # Wave-timeline attribution (opt-in, telemetry/attribution.py):
        # fences each wave at phase boundaries and classifies its wall
        # into device/host_probe/evict/table_grow/checkpoint/compile/gap.
        # Results stay bit-identical — the fences change pacing only.
        self._init_attribution("tpu_bfs", attribution)
        self._ingested = 0
        self._ingest_lock = threading.Lock()
        self._done_event = threading.Event()
        self._error: Optional[BaseException] = None
        # Preemption (checking-as-a-service): request_preempt() asks the
        # worker to suspend at the next wave/drain boundary; the run's
        # state drains into an in-memory checkpoint payload instead of a
        # file and the worker exits (see request_preempt).
        self._preempt_event = threading.Event()
        self._preempt_payload: Optional[dict] = None
        # Async pipelined wave engine (README "Async pipeline"): one FIFO
        # host worker (checker/pipeline.py) applies each wave's host-tier
        # verdict — two-phase probe, parent-fp log, survivor re-entry —
        # plus eviction absorbs and checkpoint pickles, while the device
        # runs the next wave. FIFO submission order reproduces the
        # synchronous path's exact tier-operation sequence, and epoch
        # barriers (drain) at checkpoint/preempt/queue-empty boundaries
        # make every observable snapshot identical.
        self._async = bool(async_pipeline)
        if self._async and self._visitor is not None:
            raise ValueError(
                "async_pipeline is incompatible with a visitor: per-chunk "
                "callbacks reconstruct paths through verdicts the "
                "pipeline defers; drop the visitor or run synchronously"
            )
        self._pipe = (
            HostPipeline(name="tpu-bfs-host") if self._async else None
        )
        if self._attr is not None and self._async:
            self._attr.set_overlap_mode(True)

        # Fingerprints go through the model's view hook (e.g. actor systems
        # exclude crash flags, mirroring the host state hash).
        self._fp_fn = model.packed_fingerprint
        # Dedup keys: plain fingerprints, or — under symmetry reduction —
        # the minimum fingerprint over every actor permutation (an
        # orbit-proper canonical key; see core/batch.py for why the
        # reference's sort heuristic cannot be used on a wave BFS).
        self._symmetry_enabled = options._symmetry is not None
        if self._wave_kernel == "fused" and self._symmetry_enabled:
            raise ValueError(
                "wave_kernel='fused' does not support symmetry reduction "
                "yet (orbit-minimum keys need an in-kernel permutation "
                "sweep); use wave_kernel='staged'"
            )
        self._sym_scheme = sym_key_scheme(options._symmetry)
        self._key_fn = _make_key_fn(model, self._fp_fn, options._symmetry)
        # Fingerprint-only expansion (the byte diet, VERDICT r04 #2): when
        # the model provides ``packed_expand_fps`` + ``packed_take``, the
        # wave dedups on candidate fingerprints computed from deltas and
        # materializes ONLY the fresh lanes — candidate states never
        # round-trip through HBM. ``expand_fps``: None = auto (on when
        # supported), True = require, False = force the materializing wave.
        has_fps = supports_expand_fps(model)
        if expand_fps is None:
            # Symmetry needs candidate states for orbit keys; fps path
            # yields to the materializing wave there. The fused wave
            # stages the candidate grid in VMEM scratch, so it too runs
            # the materializing wave.
            self._use_fps = (
                has_fps
                and not self._symmetry_enabled
                and self._wave_kernel != "fused"
            )
        elif expand_fps:
            if self._wave_kernel == "fused":
                raise ValueError(
                    "expand_fps=True is incompatible with "
                    "wave_kernel='fused' (the fused wave materializes the "
                    "candidate grid in VMEM scratch); use "
                    "wave_kernel='staged'"
                )
            if not has_fps:
                raise ValueError(
                    "expand_fps=True requires the model to implement "
                    "packed_expand_fps and packed_take (and "
                    "packed_expand_fps_supported() to allow them — e.g. a "
                    "codec boundary without a per-row decomposition "
                    "vetoes the fps wave)"
                )
            if self._symmetry_enabled:
                raise ValueError(
                    "expand_fps is incompatible with symmetry reduction "
                    "(orbit keys need candidate states)"
                )
            self._use_fps = True
        else:
            self._use_fps = False
        # Device-native liveness (``liveness="device"``, README
        # "Trustworthy liveness"): the wave jits log the condition-false
        # edge relation per ``eventually`` property into a
        # capacity-budgeted device store (ops/edge_store.py; evicted to
        # storage/edge_log.py when over budget), and a run-end
        # trim+reach pass decides lasso/masked-terminal existence with a
        # concrete certificate — closing the reference's documented
        # false negative without the O(region) host post-pass. Forces
        # the materializing wave (child conditions need candidate
        # states), which the expand_fps resolution above already
        # honored via validate_liveness_mode's raise on the explicit
        # conflict.
        from .device_liveness import validate_liveness_mode

        self._live = validate_liveness_mode(
            liveness,
            symmetry=self._symmetry_enabled,
            expand_fps=(expand_fps is True),
            options=options,
        )
        if self._wave_kernel == "fused" and self._live == "device":
            raise ValueError(
                "liveness='device' is incompatible with "
                "wave_kernel='fused' (the edge-log append is not fused "
                "yet); use wave_kernel='staged' or the host liveness "
                "post-pass"
            )
        if self._live is not None:
            self._use_fps = False
        self._live_enabled = self._live == "device" and bool(self._ebit)
        self._live_paths: Dict[str, Path] = {}
        self._live_outcomes: Dict[str, dict] = {}
        self._live_store = None
        self._elog = None
        self._elog_count = 0
        self._live_ins = None
        if self._live_enabled:
            from ..storage import LivenessEdgeStore, LivenessInstruments

            # One worst-case wave appends F·A edge rows + F terminal
            # rows; the default store holds four of them so drains
            # amortize the eviction pull.
            self._elog_capacity = _pow2ceil(
                edge_log_capacity
                or 4 * (self._F_max * self._A + self._F_max)
            )
            if self._elog_capacity < self._F_max * (self._A + 1):
                raise ValueError(
                    f"edge_log_capacity={edge_log_capacity} cannot hold "
                    f"one worst-case wave "
                    f"({self._F_max * (self._A + 1)} rows)"
                )
            self._live_ins = LivenessInstruments(
                "tpu_bfs", registry=self._registry
            )
            self._live_store = LivenessEdgeStore(
                instruments=self._live_ins, spill_dir=spill_dir,
                host_budget_mib=host_budget_mib,
            )
        # State-space cartography (opt-in, telemetry/coverage.py): the
        # per-action/per-property/shape reductions ride INSIDE the wave
        # jit (one extra int32 vector per existing host exit; the deep
        # drain accumulates it in its carry), so coverage=True runs stay
        # bit-identical and coverage=False traces no extra ops at all.
        # Must precede the jit construction below — _wave reads _cov at
        # trace time.
        self._init_coverage(
            "tpu_bfs", coverage, self._A, symmetry=self._symmetry_enabled
        )
        # Fused wave megakernel (README "Fused wave megakernel"): the
        # whole wave body — expand, fingerprint, sort-dedup, VMEM
        # tile-sweep insert, compaction, properties, coverage — in ONE
        # Pallas dispatch (ops/pallas_wave.py). Off-TPU the kernel runs
        # in interpret mode: exact semantics, so tier-1/CI exercise the
        # real kernel logic on CPU. Attribution bins its dispatches under
        # the dedicated "wave_kernel" phase so the ledger shows the
        # dispatch-overhead collapse instead of mis-binning it under
        # "device".
        self._fused_spec = None
        self._device_phase = "device"
        if self._wave_kernel == "fused":
            from ..ops.pallas_wave import FusedWaveSpec

            self._fused_spec = FusedWaveSpec(
                expand=model.packed_expand,
                within_boundary=model.packed_within_boundary,
                fp_fn=self._fp_fn,
                conditions=tuple(self._conditions),
                expectations=tuple(
                    p.expectation.value for p in self._properties
                ),
                ebit=tuple(sorted(self._ebit.items())),
                action_count=self._A,
                cov_layout=self._cov_layout,
                cov_antecedents=(
                    tuple(self._cov_antecedents)
                    if self._cov_antecedents is not None
                    else ()
                ),
                interpret=jax.default_backend() != "tpu",
            )
            self._device_phase = "wave_kernel"
            # Honest packability: the packed-tenancy engine has no fused
            # wave yet, so a fused-configured job never packs.
            self.packing_reason = (
                "wave_kernel='fused' runs solo: the tenant-packed engine "
                "dispatches the staged wave only"
            )
        # Buffer donation kills the per-call copy of the big operands
        # (hash table, pool ring): every donated argnum below is audited —
        # the caller never touches the donated buffer after the call
        # (it rebinds to the returned one). The checkpoint/export reads
        # (_jit_pool_export, _jit_take) are deliberately NOT donated: the
        # exported pool / padded arrays must survive the call (checkpoints
        # happen mid-run; _jit_take slices the same padded array
        # repeatedly).
        if self._live_enabled:
            # The edge log rides the wave as a second donated operand
            # (it is rebound to the returned one every dispatch, like
            # the table).
            def _wave_live(table, elog, *rest):
                return self._wave(*((table,) + rest), elog=elog)

            self._jit_wave = jax.jit(_wave_live, donate_argnums=(0, 1))
        else:
            self._jit_wave = jax.jit(self._wave, donate_argnums=(0,))
        # (bucket width, table capacity) -> AOT-compiled wave: the ladder
        # rungs and table growths each compile once, steady state replays.
        self._wave_exec = {}
        # Deep-drain executables, one per ladder rung actually visited:
        # ``_drain_jits`` holds the width-closed jit objects, ``_drain_exec``
        # the AOT-compiled executables keyed (width, table rows, pool
        # capacity) — compiles are lazy, so a run that never leaves F_max
        # pays for exactly one drain compile.
        self._drain_jits = {}
        self._drain_exec = {}
        # Cross-job sharing: with ``aot_cache="<namespace>"`` the two
        # executable dicts come from the process-global cache instead, so
        # same-shaped waves across checker instances (the service's jobs,
        # a preempted job's resumed incarnation) never recompile. The
        # namespace asserts semantic equivalence the trace signature
        # cannot see (e.g. property conditions closing over model fields
        # outside the packed arrays); the signature guards everything it
        # can see, so a namespace reuse across different shapes/configs
        # misses instead of corrupting.
        if aot_cache is not None:
            if self._sym_scheme == CUSTOM_REP_SCHEME:
                raise ValueError(
                    "aot_cache cannot be shared under a custom "
                    "symmetry_fn: the traced key function is caller "
                    "code the cache signature cannot compare"
                )
            sig = self._aot_signature()
            self._wave_exec = shared_aot_cache(aot_cache, ("wave",) + sig)
            self._drain_exec = shared_aot_cache(aot_cache, ("drain",) + sig)
        # Disk tier of the AOT cache (warm-start plane): serialized
        # executables persist under the service dir, fenced on
        # jax-version/backend/topology so a fresh PROCESS serves its
        # first job compile-free. Probed only on in-memory misses; a
        # disk hit bypasses the compile phase entirely (the attribution
        # ledger records zero compile), a refused entry is a miss.
        self._aot_disk = None
        if aot_store is not None:
            if aot_cache is None:
                raise ValueError(
                    "aot_store requires aot_cache=<namespace>: the disk "
                    "entries inherit the namespace's semantic-equivalence "
                    "assertion (see shared_aot_cache)"
                )
            from ..storage.persist import AotDiskStore

            store = (
                aot_store
                if isinstance(aot_store, AotDiskStore)
                else AotDiskStore(aot_store)
            )
            self._aot_disk = store.binding(
                aot_cache, self._aot_signature(), registry=self._registry
            )
        self._jit_pool_zero = jax.jit(self._pool_zero, static_argnums=(0,))
        # The ring is rebound to the returned one; the pushed chunk's
        # buffers cannot alias the ring (scatter), so donating them would
        # only trade a copy for an unusable-donation warning.
        self._jit_pool_push = jax.jit(self._pool_push, donate_argnums=(0,))
        self._jit_pool_export = jax.jit(self._pool_export)
        self._jit_init = jax.jit(self._init_wave, donate_argnums=(0,))
        self._jit_take = jax.jit(self._take, static_argnums=(2,))
        self._jit_finish = jax.jit(self._finish, static_argnums=(2,))
        self._jit_materialize = jax.jit(self._materialize)
        # Only the destination table (arg 1) can alias the output; the old
        # table has a different shape and is freed by the caller's rebind.
        self._jit_rehash = jax.jit(self._rehash, donate_argnums=(1,))
        self._jit_fp_single = jax.jit(self._fp_fn)
        # (in_width, bucket) -> jitted live-lane compaction (see
        # _compact_chunk).
        self._compact_exec = {}
        self.donation_enabled = True
        self._last_dispatch = None  # (bucket, live) of the last chunk wave

        self._handles = [
            threading.Thread(target=self._run, name="tpu-bfs", daemon=True)
        ]
        self._handles[0].start()

    # -- device functions (jitted) ----------------------------------------

    def _insert_sorted(self, table, shi, slo, active):
        """Visited-set insert for a wave batch (keys sorted ascending —
        both impls rely on it: XLA for first-claim-wins tie order, Pallas
        for its single left-to-right table sweep). Off-TPU the Pallas
        kernel runs in interpret mode: exact semantics, testing speed only."""
        if self._hashset_impl == "pallas":
            from ..ops.pallas_hashset import pallas_hashset_insert

            return pallas_hashset_insert(
                table, shi, slo, active,
                interpret=jax.default_backend() != "tpu",
            )
        return hashset_insert(table, shi, slo, active)

    def _init_wave(self, table):
        states = self._model.packed_init_states()
        valid = jax.vmap(self._model.packed_within_boundary)(states)
        hi, lo = jax.vmap(self._fp_fn)(states)
        if self._symmetry_enabled:
            khi, klo = self._key_fn(states)
        else:
            khi, klo = hi, lo
        n0 = hi.shape[0]
        shi = jnp.where(valid, khi, _U32_MAX)
        slo = jnp.where(valid, klo, _U32_MAX)
        shi, slo, sidx = jax.lax.sort(
            (shi, slo, jnp.arange(n0, dtype=jnp.int32)), num_keys=2
        )
        uniq = jnp.concatenate(
            [jnp.ones((1,), bool), (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])]
        )
        wave_unique = valid[sidx] & uniq
        table, fresh, _found, pending = self._insert_sorted(
            table, shi, slo, wave_unique
        )
        out = {
            "table": table,
            "states": states,
            "valid": valid,
            "hi": hi,
            "lo": lo,
            "khi": khi,
            "klo": klo,
            "n_unique": fresh.sum(),
            "n_valid": valid.sum(),
            "overflow": pending.sum(),
        }
        if self._live_enabled:
            # Analysis roots: condition-false init states, per
            # eventually property (device_liveness.py).
            from .device_liveness import seed_root_mask

            out["root_mask"] = seed_root_mask(
                self._conditions, self._ebit, states, valid
            )
        return out

    def _wave(self, table, states, hi, lo, ebits, depth, mask, depth_cap,
              elog=None):
        if self._fused_spec is not None:
            # Fused megakernel: the entire wave body in one Pallas
            # dispatch, bit-identical out-dict (elog is refused at
            # construction, so it is always None here).
            from ..ops.pallas_wave import fused_wave

            return fused_wave(
                self._fused_spec, table, states, hi, lo, ebits, depth,
                mask, depth_cap,
            )
        model = self._model
        A = self._A
        F = hi.shape[0]
        B = F * A
        eval_mask = mask & (depth < depth_cap)

        # Property conditions on the frontier (the states being "popped").
        cond_vals = [jax.vmap(c)(states) for c in self._conditions]
        ebits_after = ebits
        for pi, b in self._ebit.items():
            ebits_after = jnp.where(
                cond_vals[pi], ebits_after & ~jnp.uint32(1 << b), ebits_after
            )

        if self._use_fps:
            # Fingerprint-only expansion: candidate fps computed from the
            # parent's component hashes + per-transition deltas; no
            # candidate state arrays exist. Validity (including
            # within-boundary) is the model's contract (core/batch.py).
            chi_g, clo_g, cvalid = jax.vmap(model.packed_expand_fps)(states)
            cvalid = cvalid & eval_mask[:, None]
            generated = cvalid.sum(dtype=jnp.int32)
            terminal = eval_mask & ~cvalid.any(axis=1)
            cvalid_flat = cvalid.reshape(B)
            chi, clo = chi_g.reshape(B), clo_g.reshape(B)
            khi, klo = chi, clo
        else:
            # Expand the F × A action grid (packed_expand: per-class fast
            # path where the model provides one, else vmap of packed_step).
            cand, cvalid = jax.vmap(model.packed_expand)(states)
            cvalid = cvalid & eval_mask[:, None]
            cvalid = cvalid & jax.vmap(
                jax.vmap(model.packed_within_boundary)
            )(cand)
            generated = cvalid.sum(dtype=jnp.int32)
            terminal = eval_mask & ~cvalid.any(axis=1)

            # Fingerprint all candidates, dedup within the wave by sorting.
            cand_flat = jax.tree_util.tree_map(
                lambda x: x.reshape((B,) + x.shape[2:]), cand
            )
            cvalid_flat = cvalid.reshape(B)
            chi, clo = jax.vmap(self._fp_fn)(cand_flat)
            # Dedup/visited-set keys (== the fingerprints unless symmetry is
            # on, when they are orbit-minimum fingerprints). Frontier rows,
            # parent pointers, and discoveries always carry the ORIGINAL
            # fingerprints so paths replay through concrete states (the
            # reference keeps original fps under symmetry too,
            # src/checker/dfs.rs:300-309).
            if self._symmetry_enabled:
                khi, klo = self._key_fn(cand_flat)
            else:
                khi, klo = chi, clo
        if elog is not None:
            # Device-native liveness: this wave's condition-false edge
            # and terminal rows, appended to the device store in-jit
            # (one scatter; natural lane order — chi/clo are the
            # pre-sort candidate fps). None of the wave's own outputs
            # depend on the log, so results are bit-identical with
            # liveness off.
            from .device_liveness import wave_edge_rows

            live_rows, live_n = wave_edge_rows(
                self._conditions, self._ebit, cond_vals, cand_flat,
                cvalid_flat, terminal, hi, lo, chi, clo, A,
            )
            from ..ops.edge_store import edge_log_append

            elog = edge_log_append(
                elog, live_rows, live_n, self._elog_capacity
            )
        if self._wave_dedup == "scatter":
            # Sort-free dedup: the duplicate-tolerant insert resolves
            # in-wave twins itself (owner-ticket tie-break), so the
            # lax.sort over the full F x A grid — 66% of the 2pc-7 wave
            # at F=8192 on CPU — disappears. Lanes keep natural order:
            # lane // A is the parent row directly.
            table, fresh, _found, pending = hashset_insert_unsorted(
                table, khi, klo, cvalid_flat
            )
            sidx = jnp.arange(B, dtype=jnp.int32)
            shi, slo = khi, klo
        else:
            shi = jnp.where(cvalid_flat, khi, _U32_MAX)
            slo = jnp.where(cvalid_flat, klo, _U32_MAX)
            shi, slo, sidx = jax.lax.sort(
                (shi, slo, jnp.arange(B, dtype=jnp.int32)), num_keys=2
            )
            uniq = jnp.concatenate(
                [
                    jnp.ones((1,), bool),
                    (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1]),
                ]
            )
            wave_unique = cvalid_flat[sidx] & uniq

            # Claim slots in the visited set; fresh lanes form the next
            # frontier.
            table, fresh, _found, pending = self._insert_sorted(
                table, shi, slo, wave_unique
            )
        overflow = pending.sum()
        n_new = fresh.sum()

        # Compact fresh lanes (sorted or natural order) into prefix slots.
        pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        out_slot = jnp.where(fresh, pos, B)
        zi = jnp.zeros((B,), jnp.int32)
        zu = jnp.zeros((B,), jnp.uint32)
        src_idx = zi.at[out_slot].set(sidx, mode="drop")
        parent_row = sidx // A
        new = {
            "hi": zu.at[out_slot].set(chi[sidx], mode="drop"),
            "lo": zu.at[out_slot].set(clo[sidx], mode="drop"),
            "ebits": zu.at[out_slot].set(ebits_after[parent_row], mode="drop"),
            "depth": zi.at[out_slot].set(depth[parent_row] + 1, mode="drop"),
        }
        if self._use_fps:
            # Fresh lanes as (parent, action) references; the consumer
            # materializes them F_max at a time (enqueue segments / the
            # drain's segment loop) so only winners are ever built.
            new["src_idx"] = src_idx
        else:
            new["states"] = jax.tree_util.tree_map(
                lambda x: x[src_idx], cand_flat
            )
        out = {
            "table": table,
            "generated": generated,
            "n_new": n_new,
            "overflow": overflow,
            "max_depth": jnp.max(jnp.where(mask, depth, 0)),
            "new": new,
            "parent_hi": zu.at[out_slot].set(hi[parent_row], mode="drop"),
            "parent_lo": zu.at[out_slot].set(lo[parent_row], mode="drop"),
        }
        if self._symmetry_enabled:
            # The visited-set keys the fresh lanes claimed (orbit-minimum
            # fps) — checkpointing needs them to rebuild the table, since
            # original fps cannot be re-keyed without states.
            out["key_hi"] = zu.at[out_slot].set(shi, mode="drop")
            out["key_lo"] = zu.at[out_slot].set(slo, mode="drop")

        # Per-property discovery scan over the evaluated frontier.
        hits, fhis, flos = [], [], []
        for i, p in enumerate(self._properties):
            if p.expectation == Expectation.ALWAYS:
                h = eval_mask & ~cond_vals[i]
            elif p.expectation == Expectation.SOMETIMES:
                h = eval_mask & cond_vals[i]
            else:  # EVENTUALLY: unmet bit at a terminal state
                b = self._ebit[i]
                h = terminal & (((ebits_after >> jnp.uint32(b)) & 1) == 1)
            idx = jnp.argmax(h)
            hits.append(h.any())
            fhis.append(hi[idx])
            flos.append(lo[idx])
        if self._properties:
            out["prop_hit"] = jnp.stack(hits)
            out["prop_hi"] = jnp.stack(fhis)
            out["prop_lo"] = jnp.stack(flos)
        if self._cov is not None:
            # Coverage reductions (telemetry/coverage.py) fused into the
            # wave: per-action fired/fresh, per-property exercise,
            # terminal/successor/depth shape stats — one extra int32
            # vector per wave, drained at the existing host exits. None
            # of the wave's own outputs depend on these, so results are
            # bit-identical with coverage off.
            exercised = []
            for pi, p in enumerate(self._properties):
                if p.expectation == Expectation.ALWAYS:
                    ant = self._cov_antecedents[pi]
                    exercised.append(
                        eval_mask & jax.vmap(ant)(states)
                        if ant is not None
                        else eval_mask
                    )
                elif p.expectation == Expectation.SOMETIMES:
                    exercised.append(eval_mask & cond_vals[pi])
                else:  # EVENTUALLY: met == the unmet bit already cleared
                    eb = self._ebit[pi]
                    exercised.append(
                        eval_mask
                        & (((ebits_after >> jnp.uint32(eb)) & 1) == 0)
                    )
            uniq_fp = uniq_key = None
            if self._symmetry_enabled:
                # Orbit compression: in-wave distinct plain fps over
                # distinct orbit keys (two extra sorts, coverage mode
                # only).
                uniq_fp = self._cov_layout.count_distinct(
                    chi, clo, cvalid_flat
                )
                uniq_key = self._cov_layout.count_distinct(
                    khi, klo, cvalid_flat
                )
            out["cov"] = self._cov_layout.wave_reduce(
                eval_mask=eval_mask,
                cvalid=cvalid,
                fresh=fresh,
                lane_action=sidx % A,
                new_depth=depth[sidx // A] + 1,
                exercised=exercised,
                uniq_fp=uniq_fp,
                uniq_key=uniq_key,
            )
        # One consolidated scalar vector: each np.asarray() pull through the
        # device tunnel costs a round trip, so the host loop reads counters
        # (and property-hit flags) in a single transfer per wave.
        stats = [
            generated,
            n_new,
            pending.sum(dtype=jnp.int32),
            jnp.max(jnp.where(mask, depth, 0)),
        ]
        if self._properties:
            stats.append(out["prop_hit"].any().astype(jnp.int32))
        if elog is not None:
            out["elog"] = elog
            # Absolute fill count — the host's pre-dispatch eviction
            # decision reads it from the stats pull it already pays.
            stats.append(elog["count"])
        out["stats"] = jnp.stack(
            [s.astype(jnp.int32) for s in stats]
        )
        return out

    def _pool_zero(self, capacity):
        """An empty device frontier pool (FIFO ring of pending states)."""
        return ring_rows(self._model, capacity)

    def _pool_push(self, pool, head, count, chunk):
        """Appends a host chunk's masked lanes at the ring tail."""
        return ring_push(
            pool, head, count, chunk, chunk["mask"], self._pool_capacity
        )

    def _pool_push_fps(self, pool, head, count, new, parent_states, n_new, width):
        """Ring push for the fps wave: fresh lanes arrive as (parent,
        action) references (``new["src_idx"]``, prefix-compacted), and
        their states are materialized straight into the ring in
        ``width``-wide segments inside a dynamic-trip-count loop — real
        traffic is ``n_new`` children, never the F × A candidate grid,
        and no B-wide state buffer exists between the wave and the ring.
        ``width`` is the producing wave's lane width (the drain's bucket)."""
        A, F = self._A, width
        B = F * A
        PC = self._pool_capacity
        lanes = jnp.arange(B, dtype=jnp.int32)
        valid = lanes < n_new
        dest = jnp.where(valid, (head + count + lanes) & (PC - 1), PC)
        meta = {
            k: pool[k].at[dest].set(new[k], mode="drop")
            for k in ("hi", "lo", "ebits", "depth")
        }
        take = jax.vmap(self._model.packed_take)

        def cond(sc):
            return sc[0] * F < n_new

        def body(sc):
            seg, pstates = sc
            base = seg * F
            idxs = jax.lax.dynamic_slice_in_dim(new["src_idx"], base, F)
            parents = jax.tree_util.tree_map(
                lambda x: x[idxs // A], parent_states
            )
            childs = take(parents, idxs % A)
            seg_lanes = base + jnp.arange(F, dtype=jnp.int32)
            m = seg_lanes < n_new
            d = jnp.where(m, (head + count + seg_lanes) & (PC - 1), PC)
            pstates = jax.tree_util.tree_map(
                lambda dst, src: dst.at[d].set(src, mode="drop"),
                pstates,
                childs,
            )
            return seg + 1, pstates

        _, pstates = jax.lax.while_loop(
            cond, body, (jnp.int32(0), pool["states"])
        )
        return {"states": pstates, **meta}, count + n_new

    def _pool_take(self, pool, head, count, width=None):
        """Dequeues up to ``width`` (default ``F_max``) lanes from the
        ring head as a frontier."""
        return ring_take(
            pool, head, count, self._pool_capacity,
            self._F_max if width is None else width,
        )

    def _pool_export(self, pool, head, count):
        """The ring contents in FIFO order (for checkpointing), padded to
        the full pool width with the valid-lane mask attached."""
        return ring_export(pool, head, count, self._pool_capacity)

    def _grow_pool(self, pool, head, count):
        """Doubles the ring, preserving FIFO order (export + re-push). The
        dependent jits retrace automatically on the new shapes."""
        exported = self._jit_pool_export(pool, head, count)
        self._pool_capacity *= 2
        pool = self._jit_pool_zero(self._pool_capacity)
        pool, count = self._jit_pool_push(
            pool, jnp.int32(0), jnp.int32(0), exported
        )
        return pool, jnp.int32(0), count

    def _deep_drain(self, width, table, pool, head, count, undiscovered,
                    budget, depth_cap, elog=None):
        """Runs the BFS inside one device ``while_loop``: each iteration
        pushes the previous wave's fresh states into the FIFO ring, dequeues
        the next ``width`` lanes, and expands them. The loop exits to the
        host only when a wave is *unconsumable* device-side: the parent-fp
        log is full, the visited set needs growing, an undiscovered property
        hit, the ring would overflow, or a hash probe overflowed. Host round
        trips (the dominant cost through a device tunnel, and still the
        per-wave floor on locally-attached chips) are thus amortized over
        entire BFS phases instead of paid per wave (SURVEY §7-5c).

        ``width`` (static) is the drain's wave width — a rung of the
        occupancy-adaptive bucket ladder, so a sparse pending frontier
        drains at e.g. ``F_max/16`` lanes per wave instead of burning
        ``F_max``-wide expand grids on masked padding. The host picks the
        rung from the exact ring count at each drain entry (lazily
        AOT-compiling new rungs), and the loop additionally exits when the
        ring backlog outgrows the rung (``count > width`` with a wider
        rung available) so a growing frontier promotes itself back up the
        ladder. The popped lane sequence is width-independent (strict
        FIFO), so results are bit-identical across rungs.

        Returns the final (unconsumed) wave output, the frontier that
        produced it (for overflow retry), the ring, accumulated totals for
        the consumed waves, and their (child, parent[, key]) log entries.
        """
        F, A = width, self._A
        B = F * A
        L = self._drain_log_capacity
        PC = self._pool_capacity
        P = len(self._properties)

        def wave_of(tbl, fr, el=None):
            return self._wave(
                tbl,
                fr["states"],
                fr["hi"],
                fr["lo"],
                fr["ebits"],
                fr["depth"],
                fr["mask"],
                depth_cap,
                elog=el,
            )

        frontier0, head, count = self._pool_take(pool, head, count, F)
        out0 = wave_of(table, frontier0, elog)
        zl = jnp.zeros((L,), jnp.uint32)
        log0 = {
            "child_hi": zl,
            "child_lo": zl,
            "parent_hi": zl,
            "parent_lo": zl,
        }
        if self._symmetry_enabled:
            log0.update(key_hi=zl, key_lo=zl)
        carry = {
            "pool": pool,
            "head": head,
            "count": count,
            "frontier": frontier0,
            "out": out0,
            "log": log0,
            "log_n": jnp.int32(0),
            "generated": jnp.int32(0),
            "consumed_unique": jnp.int32(0),
            "max_depth": jnp.int32(0),
            "budget": budget,
            # The pre-loop wave (out0) counts against the cap too, so a
            # drain runs at most max_drain_waves waves total (the cap backs
            # the checkpoint-durability guarantee).
            "waves": jnp.int32(1),
            # Live lanes dispatched (the drain's compaction-ratio
            # numerator; the denominator is waves × width, host-side).
            "live_sum": frontier0["mask"].sum(dtype=jnp.int32),
        }
        if self._cov is not None:
            # Consumed waves' coverage vectors accumulate in the carry
            # (all slices are additive counts); the final unconsumed
            # wave's vector rides out["cov"] and is consumed host-side.
            carry["cov_acc"] = jnp.zeros(
                (self._cov_layout.size,), jnp.int32
            )

        def cond(c):
            o = c["out"]
            n_new = o["n_new"]
            ok = (n_new > 0) | (c["count"] > 0)
            ok &= o["overflow"] == 0
            if P:
                ok &= ~(o["prop_hit"] & undiscovered).any()
            ok &= c["log_n"] + n_new <= L
            ok &= c["count"] + n_new <= PC
            if elog is not None:
                # The edge store must absorb another worst-case wave
                # (B edge rows + F terminal rows) or the host must
                # evict first.
                ok &= o["elog"]["count"] + (B + F) <= self._elog_capacity
            if F < self._F_max:
                # Promote-exit: a backlog beyond one more wave means the
                # frontier outgrew this rung — hand back to the host,
                # which re-enters at the bucket the exact count selects.
                ok &= c["count"] <= F
            # Insert budget must survive consuming this wave plus another
            # full worst-case wave (B candidates).
            ok &= c["budget"] - n_new >= B
            ok &= c["waves"] < self._max_drain_waves
            # The generated counter is device int32 (no x64); exit to the
            # host (which accumulates in a Python int) long before a
            # billion-generated drain could wrap it.
            ok &= c["generated"] < jnp.int32(1 << 30)
            return ok

        def body(c):
            o = c["out"]
            n_new = o["n_new"]
            new = o["new"]
            lanes = jnp.arange(B, dtype=jnp.int32)
            valid = lanes < n_new
            slot = jnp.where(valid, c["log_n"] + lanes, L)
            log = dict(c["log"])
            log["child_hi"] = log["child_hi"].at[slot].set(
                new["hi"], mode="drop"
            )
            log["child_lo"] = log["child_lo"].at[slot].set(
                new["lo"], mode="drop"
            )
            log["parent_hi"] = log["parent_hi"].at[slot].set(
                o["parent_hi"], mode="drop"
            )
            log["parent_lo"] = log["parent_lo"].at[slot].set(
                o["parent_lo"], mode="drop"
            )
            if self._symmetry_enabled:
                log["key_hi"] = log["key_hi"].at[slot].set(
                    o["key_hi"], mode="drop"
                )
                log["key_lo"] = log["key_lo"].at[slot].set(
                    o["key_lo"], mode="drop"
                )
            # Push the fresh (compacted-prefix) lanes at the ring tail, then
            # dequeue the next frontier from the head — strict FIFO keeps
            # exact BFS order, so parent pointers stay shortest-path.
            if self._use_fps:
                pool, count = self._pool_push_fps(
                    c["pool"],
                    c["head"],
                    c["count"],
                    new,
                    c["frontier"]["states"],
                    n_new,
                    F,
                )
            else:
                pool, count = self._pool_push(
                    c["pool"],
                    c["head"],
                    c["count"],
                    {
                        "states": new["states"],
                        "hi": new["hi"],
                        "lo": new["lo"],
                        "ebits": new["ebits"],
                        "depth": new["depth"],
                        "mask": valid,
                    },
                )
            frontier, head, count = self._pool_take(pool, c["head"], count, F)
            nxt = {
                "pool": pool,
                "head": head,
                "count": count,
                "frontier": frontier,
                "out": wave_of(
                    o["table"], frontier,
                    o["elog"] if elog is not None else None,
                ),
                "log": log,
                "log_n": c["log_n"] + n_new,
                "generated": c["generated"] + o["generated"],
                "consumed_unique": c["consumed_unique"] + n_new,
                "max_depth": jnp.maximum(c["max_depth"], o["max_depth"]),
                "budget": c["budget"] - n_new,
                "waves": c["waves"] + 1,
                "live_sum": c["live_sum"]
                + frontier["mask"].sum(dtype=jnp.int32),
            }
            if self._cov is not None:
                nxt["cov_acc"] = c["cov_acc"] + o["cov"]
            return nxt

        res = jax.lax.while_loop(cond, body, carry)
        # One consolidated transfer for the consumed-wave bookkeeping, and
        # the log columns stacked into a single array so the host pulls the
        # whole drain's parent-fp stream in one more transfer.
        res["drain_stats"] = jnp.stack(
            [
                res["log_n"],
                res["generated"],
                res["consumed_unique"],
                res["max_depth"],
                res["waves"],
                res["count"],
                res["live_sum"],
            ]
        )
        cols = ["child_hi", "child_lo", "parent_hi", "parent_lo"]
        if self._symmetry_enabled:
            cols += ["key_hi", "key_lo"]
        res["log_pack"] = jnp.stack([res["log"][c] for c in cols])
        return res

    def _take(self, arrs, start, size):
        return jax.tree_util.tree_map(
            lambda x: jax.lax.dynamic_slice_in_dim(x, start, size, axis=0), arrs
        )

    def _finish(self, arrs, n_new, target):
        """Pads chunk arrays to ``target`` rows and attaches the lane mask.

        Wave outputs are compacted (valid rows form a prefix), so the mask
        derives from ``n_new``; the init frontier arrives uncompacted with
        an explicit ``mask`` that is padded through instead.
        """
        has_mask = "mask" in arrs

        def pad(x):
            n = x.shape[0]
            if n == target:
                return x
            widths = [(0, target - n)] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths)

        out = jax.tree_util.tree_map(pad, arrs)
        if not has_mask:
            out["mask"] = jnp.arange(target, dtype=jnp.int32) < n_new
        return out

    def _rehash(self, old_table, new_table):
        active = (old_table[:, 0] != 0) | (old_table[:, 1] != 0)
        new_table, _fresh, _found, pending = hashset_insert(
            new_table, old_table[:, 0], old_table[:, 1], active
        )
        return new_table, pending.sum()

    def _aot_signature(self) -> tuple:
        """Everything baked into the wave/drain traces that the shared
        AOT cache must key on (runtime args — depth cap, budget,
        undiscovered mask — excluded; runtime SHAPES — table rows, bucket
        width, pool capacity — ride the per-entry keys)."""
        return (
            jax.default_backend(),
            packed_model_digest(self._model, self._A),
            tuple(
                (p.name, str(p.expectation)) for p in self._properties
            ),
            self._sym_scheme,
            self._use_fps,
            self._wave_dedup,
            self._hashset_impl,
            self._wave_kernel,
            self._cov is not None,
            self._F_max,
            tuple(self._buckets),
            self._drain_log_capacity,
            self._max_drain_waves,
            self._max_capacity,
            self._live_enabled,
            self._elog_capacity if self._live_enabled else None,
        )

    # -- host exploration loop ---------------------------------------------

    def _run(self):
        try:
            if self._profile_dir:
                jax.profiler.start_trace(self._profile_dir)
                try:
                    self._explore()
                finally:
                    jax.profiler.stop_trace()
            else:
                self._explore()
        except BaseException as e:  # noqa: BLE001 - surfaced via worker_error
            self._error = e
            self._abort_attribution()
        finally:
            # The pipeline must be quiescent before done is observable:
            # counters/logs a late verdict would mutate are read the
            # moment join() returns.
            self._shutdown_pipeline()
            self._finalize_coverage(set(self._discoveries_fp))
            self._done_event.set()

    def _grow_table(self, table, min_capacity, defer_evict=False):
        """Grows (or, under an HBM budget, evicts) the device table.
        ``defer_evict=True`` — async wave loop only — hands the tier
        absorb to the pipeline worker; deep-drain and restore callers
        keep it synchronous because they branch on ``tier.is_empty()``
        immediately afterwards (the out-of-core handoff)."""
        if (
            self._max_capacity is not None
            and min_capacity > self._max_capacity
        ):
            return self._evict_l0(table, defer=defer_evict)
        capacity = self._capacity
        while capacity < min_capacity:
            capacity *= 2
        while True:
            with self._tracer.span(
                "tpu_bfs.table_grow", from_capacity=self._capacity,
                to_capacity=capacity,
            ), self._phase("table_grow"):
                new_table, leftover = self._jit_rehash(
                    table, hashset_new(capacity)
                )
                if self._attr is not None:
                    self._attr.fence(new_table)
            if not int(leftover):
                break
            # A pathological key cluster can exhaust the probe cap during
            # rehash; that costs capacity (the next doubling shortens
            # probe chains), never the run. Under an HBM budget the next
            # doubling may not exist — evict instead.
            capacity *= 2
            if (
                self._max_capacity is not None
                and capacity > self._max_capacity
            ):
                return self._evict_l0(table, defer=defer_evict)
        self._capacity = capacity
        self._wi.table_grows.inc()
        self._wi.capacity.set(capacity)
        return new_table

    def _evict_l0(self, table, defer=False):
        """Budget-capped growth: drains the FULL device table to a host
        L1 run (delta-compressed, Bloom-fronted) and resets it — the
        out-of-core alternative to doubling. Capacity settles at the
        budget cap; the emptied table carries the hot working set from
        here on while older fingerprints answer through the host probe.

        ``defer=True`` (async wave loop): the device-serial half — table
        pull + reset — stays here, but the host absorb (run build, LSM
        merges, spills) rides the pipeline worker. FIFO keeps it ordered
        exactly as the synchronous path would: after every
        already-submitted wave verdict (whose fresh keys this eviction
        now holds) and before every later one (whose probes must see
        these keys)."""
        with self._phase("evict"):
            tab = np.asarray(table)
            live = (tab[:, 0] != 0) | (tab[:, 1] != 0)
            keys = (
                tab[live, 0].astype(np.uint64) << np.uint64(32)
            ) | tab[live, 1].astype(np.uint64)
            if defer and self._pipe is not None:
                self._pipe.submit(lambda: self._evict_absorb(keys))
            else:
                self._tier.evict(keys)
            self._capacity = self._max_capacity
            self._l0_count = 0
            self._wi.capacity.set(self._capacity)
            self._tier.instruments.set_l0(0)
            return hashset_new(self._capacity)

    def _evict_absorb(self, keys):
        """Pipeline-worker half of a deferred eviction."""
        with self._phase_overlapped("evict"):
            self._tier.evict(keys)

    # -- device-native liveness (liveness="device") -------------------------

    def _maybe_evict_elog(self, defer=False) -> None:
        """Evicts the device edge store to the host tier when one more
        worst-case wave (F·A edge rows + F terminal rows) could
        overflow it."""
        self._live_ins.occupancy.set(
            self._elog_count / self._elog_capacity
        )
        if (
            self._elog_count + self._F_max * (self._A + 1)
            > self._elog_capacity
        ):
            self._evict_elog(defer=defer)

    def _evict_elog(self, defer=False) -> None:
        """Drains the filled prefix of the device edge store into the
        host :class:`~..storage.LivenessEdgeStore` and resets the fill
        count. The device pull stays on the checker thread; with
        ``defer=True`` (async mode) the host absorb — dedup, budget
        spill — rides the FIFO pipeline worker, shadowed under the next
        dispatch."""
        n = self._elog_count
        if self._elog is None or n == 0:
            return
        if n > self._elog_capacity:
            raise RuntimeError(
                "liveness edge store overflowed despite headroom checks "
                f"({n} > {self._elog_capacity}); this is a bug"
            )
        from ..ops.edge_store import EDGE_COLS

        with self._tracer.span("tpu_bfs.liveness.evict", rows=n):
            cols = {c: np.asarray(self._elog[c])[:n] for c in EDGE_COLS}
            if defer and self._pipe is not None:
                self._pipe.submit(
                    lambda: self._live_store.absorb(**cols)
                )
            else:
                self._live_store.absorb(**cols)
            self._elog = dict(self._elog, count=jnp.int32(0))
            self._elog_count = 0
        self._live_ins.occupancy.set(0.0)

    def _flush_live_edges(self) -> None:
        """Analysis/checkpoint pre-hook (base's liveness runner): the
        single-device checker keeps the edge store device-resident, so
        it must drain before any host read."""
        self._evict_elog()

    def _set_warmup(self, seconds: float) -> None:
        """First-result warmup stamp, mirrored into telemetry so traces
        carry the warmup/steady split the benches subtract."""
        self.warmup_seconds = seconds
        self._wi.warmup.set(seconds)
        self._tracer.instant("tpu_bfs.warmup_complete", warmup_s=seconds)

    def _explore(self):
        t_start = time.perf_counter()
        # Wall-clock burned before the first wave returns — dominated by XLA
        # compilation; benchmarks subtract it to report steady-state rate.
        self.warmup_seconds: Optional[float] = None
        if self._live_enabled:
            from ..ops.edge_store import edge_log_new

            self._elog = edge_log_new(self._elog_capacity)
        if self._resume_from is not None:
            table, queue = self._restore(self._resume_from)
        else:
            table, queue = self._seed()
        depth_cap = jnp.int32(self._depth_cap)
        # Deep drain is off when a visitor needs per-chunk callbacks or a
        # target caps the run (overshoot would span whole drains instead of
        # single waves).
        # A resumed out-of-core run (non-empty L1/L2) needs the per-wave
        # host probe immediately, which only the wave path performs.
        if (
            self._max_drain_waves > 1
            and self._visitor is None
            and self._target_state_count is None
            and (self._tier is None or self._tier.is_empty())
        ):
            # A non-None return is the out-of-core handoff: the first L0
            # eviction ended deep-drain mode and the remaining frontier
            # continues on the wave path. Unwinding _explore_deep's frame
            # first releases the abandoned device ring (its locals pin
            # pool-capacity lanes of packed state in HBM otherwise).
            handoff = self._explore_deep(table, queue, depth_cap, t_start)
            if handoff is not None:
                table, queue = handoff
                self._explore_waves(table, queue, depth_cap, t_start)
        else:
            self._explore_waves(table, queue, depth_cap, t_start)
        # Sound `eventually` verdicts (liveness="device"): decide
        # cycle/masked-terminal existence over the logged
        # condition-false edge relation, with a concrete certificate.
        self._run_liveness_analysis("tpu_bfs")

    def _compact_chunk(self, chunk, width):
        """Gathers a chunk's live lanes into a dense prefix and narrows it
        to ``width`` (the chosen bucket), so masked padding lanes never
        reach the expand grid. The cumsum scatter is stable — live lanes
        keep their relative order, so in-wave dedup tie-breaks (first
        claim wins by lane order) pick the same winner as the fixed-width
        dispatch and the bucketed path stays bit-identical."""
        key = (chunk["hi"].shape[0], width)
        fn = self._compact_exec.get(key)
        if fn is None:

            def compact(c):
                mask = c["mask"]
                pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
                # Scatter straight into the bucket-wide buffer (the
                # chosen bucket holds every live lane by construction;
                # the < width guard only drops lanes a torn mask could
                # produce) — a full-width scatter sliced afterwards
                # would still write O(F_max) bytes per leaf.
                dest = jnp.where(mask & (pos < width), pos, width)

                def scat(x):
                    z = jnp.zeros((width,) + x.shape[1:], x.dtype)
                    return z.at[dest].set(x, mode="drop")

                out = {
                    k: (
                        jax.tree_util.tree_map(scat, v)
                        if k == "states"
                        else scat(v)
                    )
                    for k, v in c.items()
                    if k != "mask"
                }
                out["mask"] = (
                    jnp.arange(width, dtype=jnp.int32)
                    < mask.sum(dtype=jnp.int32)
                )
                return out

            fn = jax.jit(compact)
            self._compact_exec[key] = fn
        return fn(chunk)

    def _call_wave(self, table, chunk, depth_cap):
        """Runs one wave through an AOT-compiled executable keyed on
        (bucket width, table capacity) — the only shapes that vary at
        runtime. The chunk is dispatched at the smallest ladder bucket
        holding its live lanes (compacted to a dense prefix first), so
        wave cost scales with occupancy instead of F_max. Returns
        ``(wave_out, dispatched_chunk)`` — the caller must enqueue /
        materialize against the *dispatched* chunk, whose lane indices the
        wave's parent references point into.

        Explicit AOT keeps warmup accounting exact: a compile triggered
        mid-run (table growth or a new ladder rung) is measured and moved
        into ``warmup_seconds`` instead of polluting the steady-state
        window. During the initial pre-first-result window
        ``warmup_seconds`` is still None and the caller's own stamp covers
        the compile."""
        # Injection seam, PRE-dispatch: a device wave raise (XLA error,
        # HBM OOM, tunnel drop) fires before any counter for this wave
        # mutates — the retry-from-checkpoint path never sees a
        # half-applied wave.
        fault_point("device.wave")
        if self._live_enabled:
            # Edge-store headroom for this wave's worst case (B edge
            # rows + F terminal rows) — evict to the host tier first
            # when the device store could overflow.
            self._maybe_evict_elog(defer=self._pipe is not None)
        f_in = chunk["hi"].shape[0]
        if (
            len(self._buckets) > 1
            and f_in == self._F_max
            # A table-growth retry re-dispatches the SAME logical wave
            # (same chunk, _last_dispatch already recorded): selecting and
            # counting again would double the bucket_dispatch histogram
            # and re-pay the blocking live-count pull.
            and self._last_dispatch is None
        ):
            # One tiny transfer to learn the live count; the wave-at-a-time
            # path already syncs per wave (np.asarray of the stats vector),
            # so this adds a second scalar-sized pull, not a new regime.
            live = int(np.asarray(chunk["mask"].sum()))
            width = bucket_for(self._buckets, live)
            if width < f_in:
                chunk = self._compact_chunk(chunk, width)
            self._record_dispatch(width, live)
        args = (
            table,
            chunk["states"],
            chunk["hi"],
            chunk["lo"],
            chunk["ebits"],
            chunk["depth"],
            chunk["mask"],
            jnp.asarray(depth_cap, jnp.int32),
        )
        if self._live_enabled:
            args = (table, self._elog) + args[1:]
        key = (table.shape[0], chunk["hi"].shape[0])
        exe = self._wave_exec.get(key)
        if exe is not None and self._aot_disk is not None:
            # Warm-memory / cold-disk: backfill so a later fresh process
            # still finds the artifact (one existence probe per key).
            self._aot_disk.ensure("wave", key, exe)
        if exe is None and self._aot_disk is not None:
            # Disk tier of the AOT cache: a fenced hit deserializes the
            # executable OUTSIDE the compile phase/span — the whole
            # point is that the attribution ledger records no compile.
            exe = self._aot_disk.load("wave", key)
            if exe is not None:
                self._wave_exec[key] = exe
        if exe is None:
            t0 = time.perf_counter()
            # AOT-cache miss == a compile is about to happen: the ONE
            # place the attribution engine can detect first-dispatch rung
            # compiles (the cache hit path never enters this branch).
            with self._tracer.span(
                "tpu_bfs.compile", table_capacity=key[0], frontier=key[1]
            ), self._phase("compile"):
                exe = self._jit_wave.lower(*args).compile()
            self._wave_exec[key] = exe
            if self.warmup_seconds is not None:
                self.warmup_seconds += time.perf_counter() - t0
                self._wi.warmup.set(self.warmup_seconds)
            if self._aot_disk is not None:
                self._aot_disk.save("wave", key, exe)
        if self._attr is None:
            out = exe(*args)
        else:
            # Attribution mode: fence the wave output so the device-class
            # phase ("device", or "wave_kernel" under the fused
            # megakernel) measures dispatch + device compute, not async
            # launch latency.
            with self._attr.phase(self._device_phase):
                out = exe(*args)
                self._attr.fence(out)
        if self._live_enabled:
            # Rebind the donated edge log to the wave's output.
            self._elog = out["elog"]
        return out, chunk

    def _audit_table(self, table):
        """Run-end audit of the probabilistic machinery: the device hash
        set's probe-length distribution, observed into the
        ``tpu_bfs.hashset.probe_length`` histogram (attribution mode
        only — the table pull is a full HBM read)."""
        if self._attr is None:
            return
        from ..ops.hashset import hashset_probe_length_counts

        self._attr.observe_probe_lengths(
            hashset_probe_length_counts(np.asarray(table))
        )

    def _record_dispatch(self, width, live):
        """One bucketed dispatch's telemetry (gauges + per-rung counter);
        the live/width pair is kept for the wave span's args."""
        self._last_dispatch = (width, live)
        self._wi.bucket.set(width)
        self._wi.bucket_dispatch(width)
        self._wi.compaction.set(live / width if width else 0.0)
        self._wi.frontier_fill.set(live / self._F_max)

    def _consume_wave(self, table, wave, chunk, queue, depth_cap, span=None,
                      pending=None):
        """Applies one wave output host-side (counters, discoveries, log,
        requeue), retrying the producing frontier after table growth until
        no probe overflows. Returns ``(table, wave_new)`` — the updated
        table and the wave's fresh-unique count (the deep loop uses it as
        the exact live size of the chunks spilled into the host queue).
        ``span`` (optional, a telemetry span covering this wave) is filled
        with the per-wave quantities the acceptance trace carries;
        ``pending`` (deep-drain path) is the ring's residual count, so the
        span's ``live_lanes`` = pending + this wave's spill — the exact
        live frontier at the drain boundary."""
        attempt = 0
        generated = 0
        wave_new = 0
        stale_total = 0
        self._last_dispatch = None
        while True:
            if wave is None:
                # Rebind to the dispatched (bucketed/compacted) chunk: the
                # wave's parent references index into ITS lanes.
                wave, chunk = self._call_wave(table, chunk, depth_cap)
            table = wave["table"]
            # Single host transfer per wave: [generated, n_new, overflow,
            # max_depth, any_prop_hit?, edge_count?]; per-property
            # fingerprints are pulled only on a hit.
            stats = np.asarray(wave["stats"])
            if self._live_enabled:
                self._elog_count = int(stats[-1])
            if self._cov is not None:
                # One extra (small) pull per wave in coverage mode; a
                # table-growth retry re-expands the same frontier, so
                # only the fresh-based slices accumulate then.
                self._cov.consume_device(
                    np.asarray(wave["cov"]),
                    self._cov_layout,
                    first_attempt=(attempt == 0),
                    max_depth=int(stats[3]),
                )
            if attempt == 0:
                generated = self._apply_wave_stats(wave, stats, chunk)
            n_new = int(stats[1])
            keep, k64, survivors, n_stale = self._probe_fresh(wave, n_new)
            stale_total += n_stale
            self._l0_count += n_new
            wave_new += survivors
            self._unique_count += survivors
            if survivors:
                self._log_wave(wave, n_new, keep, k64)
                # Lane width of the DISPATCHED chunk (the bucket), so the
                # enqueue padding target scales with the bucket instead of
                # re-inflating every sparse wave's output to F_max × A.
                self._enqueue(
                    queue, wave, n_new, chunk["hi"].shape[0] * self._A,
                    chunk, keep,
                )
            if not int(stats[2]):
                self._record_wave_metrics(
                    span, chunk["hi"].shape[0], generated, wave_new,
                    stale=stale_total, pending=pending,
                )
                if self._cov is not None:
                    self._cov.emit_wave_span()
                return table, wave_new
            if self._max_capacity is not None and attempt >= 8:
                # Pathological probe-window cluster: the wave overflows
                # even a freshly-evicted budget-capped table — a
                # configuration error, not a loop to spin in (mirrors
                # the sharded checker's guard).
                raise RuntimeError(
                    "a wave's candidates overflow the budget-capped "
                    "device table after repeated evictions; raise "
                    "hbm_budget_mib or shrink frontier_capacity"
                )
            table = self._grow_table(table, self._capacity * 2)
            attempt += 1
            wave = None

    def _probe_fresh(self, wave, n_new, overlapped=False):
        """The two-phase probe for one wave attempt's fresh prefix
        (out-of-core mode): the device table only vouches for the keys
        it currently holds — L0-fresh lanes whose key lives in an
        evicted L1/L2 run are STALE and must not be re-counted,
        re-logged, or re-expanded. One batched host probe per wave
        (Bloom prefilter + block binary search). ONE site for the sync
        path and the async verdict job — the key selection and stale
        gather must never diverge between them. ``overlapped`` picks
        the attribution ledger (worker-thread time is shadowed, not
        serial wall). Returns ``(keep, k64, survivors, n_stale)``."""
        keep = None
        k64 = None
        survivors = n_new
        n_stale = 0
        if (
            n_new
            and self._tier is not None
            and not self._tier.is_empty()
        ):
            phase = self._phase_overlapped if overlapped else self._phase
            with phase("host_probe"):
                if self._symmetry_enabled:
                    k64 = fp64_pairs(
                        wave["key_hi"][:n_new], wave["key_lo"][:n_new]
                    )
                else:
                    k64 = fp64_pairs(
                        wave["new"]["hi"][:n_new],
                        wave["new"]["lo"][:n_new],
                    )
                stale = self._tier.probe(k64)
            n_stale = int(stale.sum())
            if n_stale:
                keep = np.flatnonzero(~stale).astype(np.int32)
                survivors = n_new - n_stale
        return keep, k64, survivors, n_stale

    def _apply_wave_stats(self, wave, stats, chunk=None):
        """First-attempt device bookkeeping shared by the sync and async
        consume paths (a growth retry re-expands the same frontier, so
        this runs once per wave): generated/depth counters, discovery
        fingerprints, and the visitor callback. ONE site on purpose —
        the bit-identical guarantee depends on both paths applying the
        same stats the same way. Returns the wave's generated count."""
        generated = int(stats[0])
        self._state_count += generated
        self._max_depth = max(self._max_depth, int(stats[3]))
        props = self._properties
        if props and stats[4]:
            hit = np.asarray(wave["prop_hit"])
            phi = np.asarray(wave["prop_hi"])
            plo = np.asarray(wave["prop_lo"])
            for i, p in enumerate(props):
                if hit[i] and p.name not in self._discoveries_fp:
                    self._discoveries_fp[p.name] = fp_to_int(phi[i], plo[i])
        if chunk is not None and self._visitor is not None:
            self._visit_chunk(chunk)
        return generated

    def _consume_wave_async(self, table, chunk, queue, depth_cap, wave_no):
        """Device half of one wave (async pipeline mode), on the checker
        thread: dispatch, stats pull, counters/discoveries, and the
        growth/eviction retry loop — everything the NEXT dispatch
        decision depends on. The host-tier verdict of each attempt is
        submitted to the pipeline worker *before* any growth/eviction
        that follows it, so the tier sees probes and evictions in the
        synchronous order (an eviction holds the attempt's fresh keys —
        probing after absorbing them would mark the whole wave stale).
        Returns the updated table; survivors re-enter via the worker."""
        attempt = 0
        self._last_dispatch = None
        # Shared across this wave's attempt verdicts (worker-thread
        # mutation only; FIFO serializes the attempts).
        ctx = {"wave_new": 0, "stale": 0, "generated": 0}
        while True:
            wave, chunk = self._call_wave(table, chunk, depth_cap)
            table = wave["table"]
            stats = np.asarray(wave["stats"])
            if self._live_enabled:
                self._elog_count = int(stats[-1])
            if self._cov is not None:
                self._cov.consume_device(
                    np.asarray(wave["cov"]),
                    self._cov_layout,
                    first_attempt=(attempt == 0),
                    max_depth=int(stats[3]),
                )
            if attempt == 0:
                ctx["generated"] = self._apply_wave_stats(wave, stats, chunk)
            n_new = int(stats[1])
            self._l0_count += n_new
            final = not int(stats[2])
            # Point-in-time captures: by the time the verdict job runs,
            # the checker thread's live fields (dispatch, warmup,
            # l0/capacity/depth) describe a LATER wave — a deferred
            # eviction even resets l0 to 0 — so the span must carry
            # this wave's values, not a future's.
            self._pipe.submit(
                lambda w=wave, c=chunk, n=n_new, f=final,
                d=self._last_dispatch, warm=self.warmup_seconds is not None,
                st=(self._l0_count, self._capacity, self._max_depth):
                    self._wave_verdict(
                        ctx, w, c, queue, n, f, wave_no, d, warm, st
                    )
            )
            if final:
                if self._cov is not None:
                    self._cov.emit_wave_span()
                return table
            if self._max_capacity is not None and attempt >= 8:
                raise RuntimeError(
                    "a wave's candidates overflow the budget-capped "
                    "device table after repeated evictions; raise "
                    "hbm_budget_mib or shrink frontier_capacity"
                )
            table = self._grow_table(
                table, self._capacity * 2, defer_evict=True
            )
            attempt += 1

    def _wave_verdict(self, ctx, wave, chunk, queue, n_new, final, wave_no,
                      dispatch, warm, state):
        """One wave attempt's host-tier verdict, on the pipeline worker:
        the two-phase probe against the evicted runs, the parent-fp log,
        and survivor re-entry at the queue tail. Reads the wave's
        (non-donated) output buffers while the device runs the next
        wave. The final attempt emits the ``tpu_bfs.wave`` span the
        monitor's estimator and SSE stream consume — it is the first
        moment the wave's true survivor count exists."""
        def verdict():
            # tier.is_empty() inside _probe_fresh is exact HERE: every
            # eviction is applied on this same thread, in submission
            # order (the merge fence).
            keep, k64, survivors, n_stale = self._probe_fresh(
                wave, n_new, overlapped=True
            )
            ctx["stale"] += n_stale
            self._unique_count += survivors
            ctx["wave_new"] += survivors
            if survivors:
                self._log_wave(wave, n_new, keep, k64)
                self._enqueue(
                    queue, wave, n_new,
                    chunk["hi"].shape[0] * self._A, chunk, keep,
                )

        if not final:
            verdict()
            return
        # The async wave span covers the HOST VERDICT only (the device
        # half overlaps later waves) — flagged so trace readers don't
        # compare its dur against sync wave walls; wave wall in async
        # mode is the .pipeline span's wall_ms.
        with self._tracer.span(
            "tpu_bfs.wave", wave=wave_no, async_verdict=True
        ) as sp:
            verdict()
            self._record_wave_metrics(
                sp, chunk["hi"].shape[0], ctx["generated"],
                ctx["wave_new"], stale=ctx["stale"], dispatch=dispatch
                or (None, None), warm=warm, state=state,
            )

    def _save_checkpoint_maybe_async(self, queue_chunks):
        """Checkpoint at an epoch boundary. The payload snapshot is
        always built synchronously (it must capture exactly this
        boundary), but in async mode the pickle + atomic rename ride the
        pipeline worker, off the critical path. Safe because the payload
        is immutable once built (numpy copies of the chunks, exported
        parent arrays, immutable run-state snapshots) and FIFO runs the
        write before any later-submitted eviction.

        ``queue_chunks`` is the LIVE pending-frontier container: it is
        snapshotted only after the epoch barrier, because in-flight
        verdicts append survivor chunks during the drain — a pre-barrier
        snapshot would checkpoint their keys (counters, parent log)
        without their frontier chunks, and the resumed run would never
        expand them."""
        if self._pipe is None:
            self.save_checkpoint(self._checkpoint_path, list(queue_chunks))
            return
        self._pipe.drain()
        payload = self.checkpoint_payload(list(queue_chunks))
        path = self._checkpoint_path
        self._pipe.submit(lambda: self._checkpoint_write(path, payload))

    def _record_wave_metrics(self, span, frontier, generated, n_new,
                             stale=None, pending=None, dispatch=None,
                             warm=None, state=None):
        """One wave's telemetry (the shared bundle does the recording).
        Occupancy is the TABLE's (L0-resident keys over capacity) — under
        tiering the global unique count keeps growing past what the
        device holds. ``dispatch``/``warm``/``state`` (= (l0, capacity,
        max_depth)) are point-in-time captures the async verdict job
        passes in — by the time it runs, the checker thread's live
        fields describe a LATER wave (a deferred eviction even resets
        l0 to 0 mid-flight)."""
        if dispatch is not None:
            bucket, live = dispatch
        else:
            bucket, live = self._last_dispatch or (None, None)
        steady = (
            warm if warm is not None else self.warmup_seconds is not None
        )
        if state is not None:
            l0, capacity, depth = state
        else:
            l0, capacity, depth = (
                self._l0_count, self._capacity, self._max_depth
            )
        # `live` stays the last DISPATCH's live lanes (the compaction
        # denominator pairs with it); the monitor-facing live frontier is
        # separate — at a deep-drain boundary it is the ring residue plus
        # this wave's spill (the next drain's bucket selector input).
        live_lanes = pending + n_new if pending is not None else live
        extra = {}
        if live_lanes is not None:
            # Live (pre-padding) lanes: the monitor's frontier fit reads
            # this over the dispatch-width `frontier` when present.
            extra["live_lanes"] = live_lanes
        if self._tier is not None:
            self._tier.instruments.set_l0(l0)
            extra["storage_stale"] = stale or 0
            # total_fps is exact on the verdict worker too: tier
            # mutations are FIFO-ordered, so at this job's position the
            # tier state matches the synchronous path's.
            extra["storage_fps"] = self._tier.total_fps
        self._wi.record(
            span,
            frontier=frontier,
            generated=generated,
            n_new=n_new,
            occupancy=l0 / capacity,
            capacity=capacity,
            max_depth=depth,
            phase="steady" if steady else "warmup",
            bucket=bucket,
            compaction_ratio=(live / bucket if bucket else None),
            **extra,
        )

    def _explore_waves(self, table, queue, depth_cap, t_start):
        """Wave-at-a-time host loop (visitor callbacks / target counts /
        out-of-core probes).

        With ``async_pipeline=True`` this loop becomes the two-deep
        pipeline: each iteration dispatches the NEXT chunk as soon as
        the previous wave's device stats are in, while the pipeline
        worker applies the previous wave's host-tier verdict. The
        dispatched wave sequence is identical to the synchronous path's
        because (a) survivors only ever re-enter at the queue TAIL —
        exactly where the synchronous path appends them — so popping the
        head early pops the same chunk, and (b) every dispatch-affecting
        decision (growth/eviction from ``_l0_count``, target caps,
        discovery exits) is made from the stats the checker thread
        already pulled, in the same order. When the queue runs dry with
        verdicts still in flight, the epoch barrier waits for their
        survivors before concluding the space is exhausted."""
        props = self._properties
        pipe = self._pipe
        chunks = 0
        last_checkpoint = time.perf_counter()
        while True:
            # Injection seam: a wedged wave (device tunnel hang, stuck
            # host probe) simulated as a sleep — what the service's
            # stall watchdog must detect and auto-preempt through.
            fault_point("wave.stall")
            if pipe is not None and not queue and pipe.pending():
                # In-flight verdicts may refill the queue (survivors
                # land one wave late); only an empty queue AFTER the
                # barrier means the space is exhausted.
                pipe.drain()
            if not queue:
                break
            if not props:
                break
            if len(self._discoveries_fp) == len(props):
                break
            if (
                self._target_state_count is not None
                and self._target_state_count <= self._state_count
            ):
                break
            if self._preempt_event.is_set():
                # Wave-granular yield point: the pending chunk queue IS
                # the whole remaining frontier here, so the checkpoint
                # payload machinery captures the run exactly (resume is
                # bit-identical — same argument as checkpoint/restore).
                # Epoch barrier first: in-flight verdicts still own part
                # of that frontier.
                if pipe is not None:
                    pipe.drain()
                self._preempt_payload = self.checkpoint_payload(list(queue))
                self._tracer.instant(
                    "tpu_bfs.preempted", chunks=len(queue), mode="wave"
                )
                return
            # The attribution window covers the whole iteration (the
            # inter-wave checkpoint and pre-grow included): its phases
            # plus the residual gap sum to this wall by construction.
            with self._wave_window():
                if (
                    self._checkpoint_path is not None
                    and chunks
                    and chunks % self._checkpoint_every == 0
                    and (time.perf_counter() - last_checkpoint)
                    >= self._checkpoint_min_interval
                ):
                    with self._phase("checkpoint"):
                        self._save_checkpoint_maybe_async(queue)
                    last_checkpoint = time.perf_counter()
                chunks += 1
                chunk = queue.popleft()
                B = chunk["hi"].shape[0] * self._A
                if (self._l0_count + B) > _MAX_LOAD * self._capacity:
                    table = self._grow_table(
                        table,
                        _pow2ceil(int((self._l0_count + B) / _MAX_LOAD)),
                        defer_evict=pipe is not None,
                    )
                if pipe is None:
                    with self._tracer.span(
                        "tpu_bfs.wave", wave=chunks
                    ) as sp, device_step_annotation("tpu_bfs.wave", chunks):
                        table, _ = self._consume_wave(
                            table, None, chunk, queue, depth_cap, span=sp
                        )
                else:
                    # Bounded pending-verdict lane set: at most
                    # max_pending waves of device output pinned at once.
                    pipe.throttle()
                    with device_step_annotation("tpu_bfs.wave", chunks):
                        table = self._consume_wave_async(
                            table, chunk, queue, depth_cap, chunks
                        )
            if self.warmup_seconds is None:
                self._set_warmup(time.perf_counter() - t_start)
        if pipe is not None:
            # Run-end epoch barrier: counters and the parent-fp log must
            # be settled before the audit and the done flag.
            pipe.drain()
        self._audit_table(table)

    def _explore_deep(self, table, queue, depth_cap, t_start):
        """Deep-drain host loop: keeps the pending frontier in the device
        ring and re-enters ``_deep_drain`` until the space is exhausted,
        paying host round trips only at drain exits."""
        props = self._properties
        if not props:
            return
        B = self._F_max * self._A
        pool = self._jit_pool_zero(self._pool_capacity)
        head = jnp.int32(0)
        count = jnp.int32(0)
        pool_count = 0  # host view; exact after each drain, bounded after pushes
        # Exact pending live lanes (ring + spilled queue) — the bucket
        # selector's input. None until the first drain exit: the first
        # drain always runs at F_max, so a run that finishes in one drain
        # (every small space) compiles exactly one rung, and the ramp-up
        # phase never ladder-climbs through the narrow rungs' compiles.
        live_est = None
        # Consecutive-entry votes per rung: a NEW rung's drain compile is
        # only paid once the same rung is selected on two consecutive
        # entries. A ramp-up phase sweeps through each narrow live count
        # once (votes never accumulate → it stays on already-compiled
        # rungs), while a persistent sparse regime selects the same rung
        # every entry and adapts on its second drain.
        rung_votes = {}
        drains = 0
        last_checkpoint = time.perf_counter()
        while True:
            # Injection seam: a wedged drain loop (see _explore_waves).
            fault_point("wave.stall")
            if len(self._discoveries_fp) == len(props):
                break
            if self._preempt_event.is_set():
                # Drain-granular yield point. Ring contents are OLDER
                # than any host-queue spill (same ordering argument as
                # _handoff_queue), so ring-then-queue preserves exact
                # FIFO and the resumed run stays bit-identical. A drain
                # yields only between drains; bound preemption latency
                # with max_drain_waves (the service spawns jobs with a
                # small cap, like the checkpoint-durability clamp).
                if self._pipe is not None:
                    # In-flight checkpoint writes must land before the
                    # worker dies with the run.
                    self._pipe.drain()
                chunks = self._export_pool_chunks(pool, head, count)
                chunks.extend(queue)
                self._preempt_payload = self.checkpoint_payload(chunks)
                self._tracer.instant(
                    "tpu_bfs.preempted", chunks=len(chunks), mode="drain"
                )
                return None
            # First L0 eviction ends deep-drain mode: from here every
            # wave's fresh set needs the host-side L1/L2 probe, which a
            # device-resident drain cannot perform mid-loop.
            if self._tier is not None and not self._tier.is_empty():
                return table, self._handoff_queue(pool, head, count, queue)
            # The host queue must FULLY drain into the ring before the next
            # drain: leftover spilled chunks are older than anything the
            # drain will push, so leaving them queued would let newer states
            # jump ahead and break exact BFS order (depth labels and
            # shortest-path parents). Grow the ring when they don't fit —
            # exact BFS inherently holds the whole pending frontier, just
            # like the reference's host queue. Push dispatches stay
            # device-side (no blocking transfer).
            while queue:
                if pool_count + self._F_max > self._pool_capacity:
                    # The host bound overcounts (F_max per push); refresh it
                    # from the device before paying for a ring doubling.
                    pool_count = int(np.asarray(count))
                    if pool_count + self._F_max > self._pool_capacity:
                        pool, head, count = self._grow_pool(pool, head, count)
                chunk = queue.popleft()
                pool, count = self._jit_pool_push(pool, head, count, chunk)
                pool_count += self._F_max
            if pool_count == 0:
                break
            # Every drain exit is a checkpoint opportunity (waves-per-drain
            # is capped when a checkpoint path is set); the time floor
            # throttles the full parent-map export + pickle.
            # Attribution window for the whole drain iteration (the
            # checkpoint, pre-grow, drain execution, and the final
            # host-consumed wave). The out-of-core handoff return closes
            # it explicitly first so the handoff's queue rebuild is not
            # misattributed to the drain; exit is idempotent, so the
            # with-block's unwind (normal, return, or exception) is safe
            # either way.
            drain_window = self._wave_window("drain")
            with drain_window:
                if (
                    self._checkpoint_path is not None
                    and drains
                    and (time.perf_counter() - last_checkpoint)
                    >= self._checkpoint_min_interval
                ):
                    # The ring is the sole pending-frontier store here: the
                    # push loop above always fully drains the host queue.
                    assert not queue
                    with self._phase("checkpoint"):
                        # Async mode: only the pickle+rename is deferred
                        # (deep drains run tier-empty, so the pipeline
                        # carries nothing else here).
                        self._save_checkpoint_maybe_async(
                            self._export_pool_chunks(pool, head, count)
                        )
                    last_checkpoint = time.perf_counter()
                drains += 1
                if (self._l0_count + B) > _MAX_LOAD * self._capacity:
                    table = self._grow_table(
                        table, _pow2ceil(int((self._l0_count + B) / _MAX_LOAD))
                    )
                    if self._tier is not None and not self._tier.is_empty():
                        # The pregrow evicted (budget hit): the queue is
                        # empty (flushed above), the ring holds the whole
                        # frontier. Close the window first so the
                        # handoff's queue rebuild is not attributed to
                        # this drain (exit is idempotent — the with's
                        # unwind after the return is a no-op).
                        drain_window.__exit__(None, None, None)
                        return table, self._handoff_queue(
                            pool, head, count, queue
                        )
                undiscovered = np.array(
                    [p.name not in self._discoveries_fp for p in props]
                )
                # Clamp: the budget rides device int32; a > 2^31-slot table
                # must saturate, not overflow.
                budget = jnp.int32(
                    min(
                        int(_MAX_LOAD * self._capacity) - self._l0_count,
                        (1 << 31) - 1 - B,
                    )
                )
                # Ladder rung for this drain: the smallest bucket holding the
                # exact pending-live count (F_max for the first drain — see
                # live_est above). A sparse steady state drains at e.g.
                # F_max/16 lanes per wave; the promote-exit inside the drain
                # hands back control if the frontier outgrows the rung.
                width = self._F_max
                if live_est is not None and len(self._buckets) > 1:
                    want = bucket_for(
                        self._buckets, max(1, min(live_est, self._F_max))
                    )
                    if want in self._drain_jits or want == self._F_max:
                        width = want
                        rung_votes = {}
                    else:
                        votes = rung_votes.get(want, 0) + 1
                        rung_votes = {want: votes}
                        if votes >= 2:
                            width = want
                        else:
                            # Not yet worth a compile: the narrowest rung
                            # already compiled that still holds the load
                            # (F_max as the floor fallback).
                            width = min(
                                (
                                    w
                                    for w in self._drain_jits
                                    if w >= want
                                ),
                                default=self._F_max,
                            )
                args = (
                    table,
                    pool,
                    head,
                    count,
                    jnp.asarray(undiscovered),
                    budget,
                    depth_cap,
                )
                if self._live_enabled:
                    # Edge-store headroom for at least one wave; the
                    # drain self-exits when the log fills mid-drain.
                    self._maybe_evict_elog()
                    args += (self._elog,)
                # Compile ahead of the real call so warmup measures pure
                # compilation: a single deep drain can run the whole
                # exploration, so "time until the first result returned"
                # (the wave path's proxy) would fold exploration into
                # warmup and corrupt steady-state rates. Mid-run compiles
                # (new rung, grown table/ring) are measured into warmup too.
                exe = self._drain_exe(width, args, t_start)
                # Injection seam, pre-dispatch (the deep-drain twin of
                # _call_wave's site): the ring still holds the frontier,
                # so nothing of this drain is half-applied on a raise.
                fault_point("device.wave")
                drain_span = self._tracer.span(
                    "tpu_bfs.drain", drain=drains, bucket=width
                )
                with drain_span, device_step_annotation("tpu_bfs.drain", drains):
                    with self._phase(self._device_phase):
                        res = exe(*args)
                        if self._attr is not None:
                            self._attr.fence(res)
                    dstats = np.asarray(res["drain_stats"])
                    log_n = int(dstats[0])
                    self._state_count += int(dstats[1])
                    self._unique_count += int(dstats[2])
                    # Drains only run while the tier is empty, so every drain
                    # fresh is also an L0 resident.
                    self._l0_count += int(dstats[2])
                    self._max_depth = max(self._max_depth, int(dstats[3]))
                    # A drain consumes many waves device-side; its span carries
                    # the aggregate (per-wave granularity would need per-wave
                    # host exits — the cost the drain exists to amortize). The
                    # drain's final, unconsumed wave is accounted by the
                    # _consume_wave call below, hence waves - 1 here.
                    self._wi.drains.inc()
                    self._wi.waves.inc(max(int(dstats[4]) - 1, 0))
                    # Bucket accounting for the drain's waves: every wave in
                    # this drain ran at ``width`` lanes; the compaction ratio
                    # is live lanes over dispatched lanes, the frontier fill
                    # live lanes over F_max capacity.
                    waves_n = int(dstats[4])
                    live_sum = int(dstats[6])
                    self._wi.bucket.set(width)
                    self._wi.bucket_dispatch(width, waves_n)
                    compaction = (
                        live_sum / (waves_n * width) if waves_n else None
                    )
                    if compaction is not None:
                        self._wi.compaction.set(compaction)
                        self._wi.frontier_fill.set(
                            live_sum / (waves_n * self._F_max)
                        )
                    self._wi.record(
                        drain_span,
                        frontier=self._F_max,
                        generated=int(dstats[1]),
                        n_new=int(dstats[2]),
                        occupancy=self._l0_count / self._capacity,
                        capacity=self._capacity,
                        max_depth=self._max_depth,
                        count_wave=False,
                        observe=False,
                        # Final unconsumed wave rides the _consume_wave span
                        # below — same minus-one as the waves counter above,
                        # so monitor /status waves match the registry.
                        waves=max(waves_n - 1, 0),
                        log_n=log_n,
                        ring_count=int(dstats[5]),
                        bucket=width,
                        compaction_ratio=compaction,
                    )
                pool, head, count = res["pool"], res["head"], res["count"]
                if self._live_enabled:
                    # Rebind the donated edge log to the drain's output
                    # (the final unconsumed wave's appends included).
                    self._elog = res["out"]["elog"]
                pool_count = int(dstats[5])
                if self._cov is not None:
                    # The drain's consumed-wave coverage aggregate (the
                    # final unconsumed wave rides _consume_wave below).
                    self._cov.consume_device(
                        np.asarray(res["cov_acc"]),
                        self._cov_layout,
                        max_depth=int(dstats[3]),
                    )
                if log_n:
                    # The whole drain's parent-fp stream in one transfer.
                    pack = np.asarray(res["log_pack"][:, :log_n])
                    self._wave_log.append(
                        (fp64_pairs(pack[0], pack[1]), fp64_pairs(pack[2], pack[3]))
                    )
                    if self._symmetry_enabled:
                        self._key_log.append(fp64_pairs(pack[4], pack[5]))
                # Consume the final (unconsumable device-side) wave the slow
                # way; its fresh chunks spill into the host queue and are fed
                # back into the ring on the next loop pass.
                with self._tracer.span("tpu_bfs.wave", drain=drains) as sp:
                    table, spilled = self._consume_wave(
                        table, res["out"], res["frontier"], queue, depth_cap,
                        span=sp, pending=pool_count,
                    )
            # Exact pending live lanes: the ring's count plus the final
            # wave's fresh spill — the next drain's bucket selector input.
            live_est = pool_count + spilled
        self._audit_table(table)

    def _handoff_queue(self, pool, head, count, queue):
        """Builds the wave-mode chunk queue for the permanent switch out
        of deep-drain mode (first L0 eviction). The device ring's
        contents are OLDER than any host-queue spill (the drain's final
        wave spilled after everything it had consumed), so the ring
        exports ahead of the queue — exact FIFO, hence exact BFS order,
        is preserved and the run stays bit-identical. The caller
        (_explore) resumes on the wave path only after _explore_deep's
        frame unwinds, releasing the ring's device buffers."""
        chunks = self._export_pool_chunks(pool, head, count)
        newq = deque(chunks)
        newq.extend(queue)
        self._tracer.instant(
            "tpu_bfs.storage.wave_mode", ring_chunks=len(chunks),
            spilled_chunks=len(queue),
        )
        return newq

    def _drain_exe(self, width, args, t_start):
        """The AOT-compiled deep drain for one ladder rung, keyed on
        (width, table rows, pool capacity) so table/ring growth recompiles
        are explicit and measured. The first compile stamps warmup; later
        compiles (new rung or grown shapes) are added to it, keeping the
        steady-state window honest."""
        key = (width, args[0].shape[0], self._pool_capacity)
        exe = self._drain_exec.get(key)
        if exe is not None and self._aot_disk is not None:
            # Warm-memory / cold-disk backfill, same as the wave site.
            self._aot_disk.ensure("drain", key, exe)
        if exe is None and self._aot_disk is not None:
            # Disk tier (warm-start plane): a fenced hit loads the rung
            # outside the compile phase — cross-process warm starts
            # record zero compile, exactly like the in-memory hit below.
            exe = self._aot_disk.load("drain", key)
            if exe is not None:
                self._drain_exec[key] = exe
        if exe is not None and self.warmup_seconds is None:
            # Warm start (shared AOT cache hit on the very first drain):
            # stamp the setup-only warmup now. Leaving it None would
            # both under-report the warm/steady split and make the
            # service's stall watchdog treat the whole run as warmup
            # (its pet condition defers to an unstamped warmup).
            self._set_warmup(time.perf_counter() - t_start)
        if exe is None:
            jit_fn = self._drain_jits.get(width)
            if jit_fn is None:

                def fn(*a, _w=width):
                    return self._deep_drain(_w, *a)

                donate = (0, 1, 7) if self._live_enabled else (0, 1)
                jit_fn = jax.jit(fn, donate_argnums=donate)
                self._drain_jits[width] = jit_fn
            t0 = time.perf_counter()
            # AOT-cache miss: the drain rung is about to compile — the
            # attribution engine's compile-detection site for drains.
            with self._tracer.span(
                "tpu_bfs.compile", kind="drain", bucket=width,
                table_capacity=key[1],
            ), self._phase("compile"):
                exe = jit_fn.lower(*args).compile()
            self._drain_exec[key] = exe
            if self.warmup_seconds is None:
                self._set_warmup(time.perf_counter() - t_start)
            else:
                self.warmup_seconds += time.perf_counter() - t0
                self._wi.warmup.set(self.warmup_seconds)
            if self._aot_disk is not None:
                self._aot_disk.save("drain", key, exe)
        return exe

    def _export_pool_chunks(self, pool, head, count):
        """The ring contents as F_max-wide host chunks (for checkpoints)."""
        exported = self._jit_pool_export(pool, head, count)
        n = int(np.asarray(count))
        chunks = []
        for start in range(0, n, self._F_max):
            chunks.append(
                self._jit_take(exported, jnp.int32(start), self._F_max)
            )
        return chunks

    def _seed(self):
        """Inserts + enqueues the initial states; returns (table, queue)."""
        table = hashset_new(self._capacity)
        while True:
            out = self._jit_init(table)
            if not int(out["overflow"]):
                break
            table = hashset_new(self._capacity * 2)
            self._capacity *= 2
        table = out["table"]
        self._state_count = int(out["n_valid"])
        self._unique_count = int(out["n_unique"])
        self._l0_count = self._unique_count
        # Seed the cumulative counters too, so the registry's totals match
        # the checker's (init states never flow through a wave).
        self._wi.generated.inc(self._state_count)
        self._wi.unique.inc(self._unique_count)
        if self._cov is not None:
            self._cov.record_seed(self._unique_count)
        hi = np.asarray(out["hi"])
        lo = np.asarray(out["lo"])
        valid = np.asarray(out["valid"])
        child64 = fp64_pairs(hi, lo)[valid]
        self._wave_log.append((child64, np.zeros_like(child64)))
        if self._symmetry_enabled:
            self._key_log.append(fp64_pairs(out["khi"], out["klo"])[valid])
        if self._live_enabled:
            self._live_store.add_roots(
                child64, np.asarray(out["root_mask"])[valid]
            )

        F0 = hi.shape[0]
        init_arrs = {
            "states": out["states"],
            "hi": out["hi"],
            "lo": out["lo"],
            "ebits": jnp.full((F0,), self._ebits0, jnp.uint32),
            "depth": jnp.ones((F0,), jnp.int32),
            "mask": out["valid"],
        }
        target0 = -(-F0 // self._F_max) * self._F_max
        padded0 = self._jit_finish(init_arrs, jnp.int32(0), target0)
        queue = deque()
        for start in range(0, F0, self._F_max):
            queue.append(self._jit_take(padded0, jnp.int32(start), self._F_max))
        return table, queue

    # -- checkpoint/resume (new capability: the reference loses all progress
    # on a kill, SURVEY §5) ------------------------------------------------

    def _model_digest(self) -> str:
        return packed_model_digest(self._model, self._A)

    def save_checkpoint(self, path, queue) -> None:
        """Atomically serializes counters, discoveries, the parent-pointer
        map, and the pending frontier chunks. The visited set is not stored
        separately — it is exactly the parent map's keys, and the device
        table is rebuilt from them on resume."""
        atomic_pickle(path, self.checkpoint_payload(queue))

    def checkpoint_payload(self, queue) -> dict:
        """The checkpoint as an in-memory payload dict (format v2, the
        exact object ``save_checkpoint`` pickles). The preempt/resume
        path round-trips this without touching disk: pass it straight to
        a new checker's ``resume_from=``."""
        self._ingest_wave_log()
        children, parents = self._store.export()
        payload = {
            **checkpoint_header(
                "tpu_bfs",
                self._model,
                self._A,
                self._symmetry_enabled,
                self._sym_scheme,
            ),
            "state_count": self._state_count,
            "unique_count": self._unique_count,
            "max_depth": self._max_depth,
            "discoveries": dict(self._discoveries_fp),
            "children": children,
            "parents": parents,
            "capacity": self._capacity,
            "chunks": [
                jax.tree_util.tree_map(np.asarray, chunk) for chunk in queue
            ],
        }
        if self._symmetry_enabled:
            payload["keys"] = (
                np.concatenate(self._key_log)
                if self._key_log
                else np.zeros((0,), np.uint64)
            )
        if self._tier is not None and not self._tier.is_empty():
            # Out-of-core: the evicted runs + Bloom filters ride the
            # checkpoint (CRC-validated on restore); the L0 set is
            # rebuilt on restore as "known keys not in any run", which
            # always fits the budget.
            payload["storage"] = self._tier.export_state()
        if self._live_enabled:
            # v3 payload extension: the condition-false edge relation
            # (device store flushed first) + roots/terminals, so a
            # resumed run's final liveness verdict never depends on
            # where the run was cut.
            self._evict_elog()
            payload["liveness"] = self._live_store.export_state()
            payload["version"] = 3
        return payload

    def _restore(self, path):
        if isinstance(path, dict):
            # In-memory resume (preempt/resume): the payload dict itself,
            # no pickle round trip.
            payload = path
        else:
            import pickle

            with open(path, "rb") as f:
                payload = pickle.load(f)
        validate_checkpoint_header(
            payload,
            "tpu_bfs",
            "sharded checkpoints carry a frontier pool, not the chunk "
            "queue this restore needs",
            self._model,
            self._A,
            self._symmetry_enabled,
            self._sym_scheme,
        )
        self._state_count = payload["state_count"]
        self._unique_count = payload["unique_count"]
        self._max_depth = payload["max_depth"]
        self._discoveries_fp = dict(payload["discoveries"])
        children = payload["children"]
        parents = payload["parents"]
        self._wave_log.append((children, parents))
        # Visited-set keys == the original fps unless symmetry was on (then
        # the checkpoint carries the orbit-key stream separately).
        keys = children
        if self._symmetry_enabled:
            keys = payload["keys"]
            self._key_log.append(keys)

        # Out-of-core checkpoints carry the evicted runs; load them first
        # (CRC-validated per run) so the L0 rebuild below inserts only
        # the keys no run holds — that set always fits the HBM budget.
        storage_state = payload.get("storage")
        if storage_state:
            if self._tier is None:
                # Restored without budget knobs: hold the runs anyway
                # (unbounded L0 from here on, probes stay correct).
                from ..storage import TieredVisitedStore

                from ..storage import StorageInstruments

                self._tier = TieredVisitedStore(
                    instruments=StorageInstruments(
                        "tpu_bfs", registry=self._registry
                    ),
                    tracer=self._tracer,
                )
            self._tier.load_state(storage_state)
        # Device-liveness state must round-trip with the run: resuming a
        # liveness="device" run without the knob (or vice versa) would
        # finish with a silently truncated edge relation — an unsound
        # verdict — so mode mismatches are refused, not papered over.
        live_state = payload.get("liveness")
        if self._live_enabled and live_state is None:
            raise ValueError(
                "liveness='device' cannot resume a checkpoint written "
                "without it: the edges explored before the checkpoint "
                "were never logged, so the final verdict would be "
                "unsound"
            )
        if live_state is not None:
            if not self._live_enabled:
                raise ValueError(
                    "checkpoint carries a liveness edge store; resume "
                    "with liveness='device' (dropping it would discard "
                    "the soundness the original run paid for)"
                )
            self._live_store.load_state(live_state)
        insert_keys = keys
        if self._tier is not None and not self._tier.is_empty():
            insert_keys = keys[~self._tier.probe(keys)]

        # Rebuild the device visited set by claim-inserting the L0 keys.
        self._capacity = max(self._capacity, payload["capacity"])
        if self._max_capacity is not None:
            self._capacity = min(self._capacity, self._max_capacity)
        table = hashset_new(self._capacity)
        hi = (insert_keys >> np.uint64(32)).astype(np.uint32)
        lo = (insert_keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        batch = 1 << 16
        if self._max_capacity is not None:
            # A batch must fit a freshly-evicted table under the load cap
            # or the grow-and-retry below could loop.
            batch = min(batch, int(self._max_capacity * _MAX_LOAD))
        for start in range(0, len(insert_keys), batch):
            bh = jnp.asarray(hi[start : start + batch])
            bl = jnp.asarray(lo[start : start + batch])
            active = jnp.ones((bh.shape[0],), bool)
            table, fresh, _found, pending = hashset_insert(
                table, bh, bl, active
            )
            self._l0_count += int(fresh.sum())
            if int(pending.sum()):
                table = self._grow_table(table, self._capacity * 2)
                table, f2, _fo, pend2 = hashset_insert(table, bh, bl, active)
                self._l0_count += int(f2.sum())
                if int(pend2.sum()):
                    raise RuntimeError("checkpoint restore overflowed table")
        queue = deque(
            jax.tree_util.tree_map(jnp.asarray, chunk)
            for chunk in payload["chunks"]
        )
        return table, queue

    def _log_wave(self, wave, n_new, keep=None, probe_keys=None):
        """Logs the wave's fresh (child, parent[, key]) fps; ``keep``
        (optional int32 positions into the fresh prefix) restricts to the
        lanes that survived the L1/L2 host probe. ``probe_keys`` is the
        u64 key array that probe already pulled for the same prefix
        (== the child fps, or the orbit keys under symmetry) — reused so
        the hot out-of-core path pays one device pull, not two."""
        if probe_keys is not None and not self._symmetry_enabled:
            child = probe_keys
        else:
            child = fp64_pairs(
                wave["new"]["hi"][:n_new], wave["new"]["lo"][:n_new]
            )
        parent = fp64_pairs(
            wave["parent_hi"][:n_new], wave["parent_lo"][:n_new]
        )
        if keep is not None:
            child, parent = child[keep], parent[keep]
        self._wave_log.append((child, parent))
        if self._symmetry_enabled:
            keys = (
                probe_keys
                if probe_keys is not None
                else fp64_pairs(
                    wave["key_hi"][:n_new], wave["key_lo"][:n_new]
                )
            )
            if keep is not None:
                keys = keys[keep]
            self._key_log.append(keys)

    def _enqueue(self, queue, wave, n_new, B, chunk, keep=None):
        if keep is not None:
            return self._enqueue_survivors(queue, wave, chunk, keep)
        target = -(-B // self._F_max) * self._F_max
        padded = self._jit_finish(dict(wave["new"]), jnp.int32(n_new), target)
        for start in range(0, n_new, self._F_max):
            piece = self._jit_take(padded, jnp.int32(start), self._F_max)
            if self._use_fps:
                # Materialize this chunk's fresh children from (parent,
                # action) references against the producing frontier —
                # ceil(n_new / F_max) materializations per wave, never the
                # full F × A grid.
                piece = self._jit_materialize(chunk["states"], piece)
            queue.append(piece)

    def _enqueue_survivors(self, queue, wave, chunk, keep):
        """Enqueue path for a host-probe-filtered wave: gathers the
        surviving lanes (host index list into the fresh prefix) into
        F_max-wide chunks. Relative lane order is preserved, so the
        frontier sequence matches the unbounded run's exactly — the keys
        dropped here are precisely the ones that run never saw fresh."""
        new = wave["new"]
        F = self._F_max
        for start in range(0, len(keep), F):
            sel = keep[start : start + F]
            idx = np.zeros((F,), np.int32)
            idx[: len(sel)] = sel
            idx_j = jnp.asarray(idx)
            piece = {
                k: (
                    jax.tree_util.tree_map(lambda x: x[idx_j], v)
                    if k == "states"
                    else v[idx_j]
                )
                for k, v in new.items()
            }
            piece["mask"] = jnp.arange(F, dtype=jnp.int32) < len(sel)
            if self._use_fps:
                piece = self._jit_materialize(chunk["states"], piece)
            queue.append(piece)

    def _materialize(self, parent_states, piece):
        """Builds one queue chunk's states via ``packed_take`` from its
        fresh-lane (parent, action) references (fps wave path). Padding
        lanes reference parent 0 / action 0 and are masked."""
        idxs = piece.pop("src_idx")
        parents = jax.tree_util.tree_map(
            lambda x: x[idxs // self._A], parent_states
        )
        piece["states"] = jax.vmap(self._model.packed_take)(
            parents, idxs % self._A
        )
        return piece

    def _visit_chunk(self, chunk):
        mask = np.asarray(chunk["mask"])
        depth = np.asarray(chunk["depth"])
        hi = np.asarray(chunk["hi"])
        lo = np.asarray(chunk["lo"])
        for i in range(len(mask)):
            if mask[i] and depth[i] < self._depth_cap:
                self._visitor.visit(
                    self._model, self._reconstruct(fp_to_int(hi[i], lo[i]))
                )

    # -- path reconstruction ----------------------------------------------

    def _host_fp(self, host_state) -> int:
        hi, lo = self._jit_fp_single(self._model.pack_state(host_state))
        return fp_to_int(hi, lo)

    def _ingest_wave_log(self):
        # Raced by the worker (visitor reconstruction) and the user thread
        # (mid-run discoveries()); must not skip a wave. First-writer-wins
        # inserts keep the shortest-path parent.
        with self._ingest_lock:
            while self._ingested < len(self._wave_log):
                children, parents = self._wave_log[self._ingested]
                self._store.insert_batch(children, parents)
                self._ingested += 1

    def _reconstruct(self, fp: int) -> Path:
        self._ingest_wave_log()
        chain = self._store.chain(fp)
        return Path.from_fingerprints(self._model, chain, fp_of=self._host_fp)

    # -- preemption (checking-as-a-service) --------------------------------

    supports_preempt = True

    def request_preempt(self) -> None:
        """Asks the worker to suspend at the next wave/drain boundary:
        the run's full state (counters, parent map, pending frontier,
        storage tiers) drains into an in-memory checkpoint payload
        (``preempt_payload()``) and the worker thread exits. Resume by
        spawning a new checker with ``resume_from=<payload>`` and the
        same configuration — the resumed run is bit-identical to an
        uninterrupted one (counts, depths, discoveries, golden reporter;
        same machinery as checkpoint/restore, minus the pickle). A run
        that finishes before reaching a yield point completes normally
        and ``preempt_payload()`` stays None."""
        self._preempt_event.set()

    # -- Checker surface ---------------------------------------------------

    @property
    def pipeline(self) -> str:
        """The expansion pipeline this run dispatches: ``"fps"``
        (fingerprint-only expansion, candidates never materialized) or
        ``"materialize"`` (the full F × A state grid). bench.py's
        measured-policy calibration compares this against the timed
        winner."""
        return "fps" if self._use_fps else "materialize"

    def model(self):
        return self._model

    def state_count(self) -> int:
        return max(self._state_count, self._unique_count)

    def unique_state_count(self) -> int:
        return self._unique_count

    def max_depth(self) -> int:
        return self._max_depth

    supports_device_liveness = True

    def discoveries(self) -> Dict[str, Path]:
        out = {
            name: self._reconstruct(fp)
            for name, fp in list(self._discoveries_fp.items())
        }
        out = self._with_device_liveness(out)
        return self._with_lassos(
            out,
            self._done_event.is_set(),
            set(self._discoveries_fp) | set(self._live_paths),
        )

    def handles(self) -> List[threading.Thread]:
        handles, self._handles = self._handles, []
        return handles

    def is_done(self) -> bool:
        return self._done_event.is_set()

    def worker_error(self) -> Optional[BaseException]:
        return self._error

    def _discovery_names(self) -> List[str]:
        # Names only — the flight recorder's digest must not trigger the
        # full path reconstruction discoveries() performs.
        return list(set(self._discoveries_fp) | set(self._live_paths))

    def state_digest(self) -> dict:
        digest = super().state_digest()
        digest.update(
            table_capacity=self._capacity,
            frontier_capacity=self._F_max,
            wave_kernel=self._wave_kernel,
            warmup_seconds=getattr(self, "warmup_seconds", None),
            checkpoint_path=self._checkpoint_path,
            last_dispatch=self._last_dispatch,
            preempted=self.preempted,
            liveness_mode=self.liveness_mode,
        )
        if self._live_store is not None:
            try:
                digest["liveness_edge_store"] = self._live_store.stats()
            except Exception:  # noqa: BLE001 - mid-crash best effort
                digest["liveness_edge_store"] = None
        if self._tier is not None:
            try:
                digest["storage"] = self._tier.instruments.bench_stats()
            except Exception:  # noqa: BLE001 - mid-crash best effort
                digest["storage"] = None
        return digest
