"""Per-wave cost attribution for the device BFS — bench instrumentation.

``TpuBfsChecker._wave`` is one fused jit on purpose (host round trips
through the device tunnel cost ~0.1-1s); a fused kernel cannot say where
wave time goes. This module mirrors the wave pipeline as SEPARATELY
jitted stages — expand / properties / fingerprint / sort-dedup / insert /
compact — drives a few real waves to reach a representative frontier,
then times each stage with ``block_until_ready`` and pulls XLA's compiled
``cost_analysis`` (FLOPs, bytes accessed) per stage. The stage split adds
dispatch overhead the fused wave does not pay, so the fused wave is timed
too and reported alongside (stage sums exceeding the fused time = the
overhead, not a lie).

The output feeds ``bench.py``'s breakdown fields: per-stage milliseconds,
bytes-per-state, and a roofline attainment figure against the chip's HBM
peak — the judgeability half of VERDICT r03 #1. The reference's analog is
its ``ReportData`` throughput surface (``/root/reference/src/report.rs:
10-98``), which has no per-phase attribution at all.

Symmetry-reduced models are not supported (none of the bench legs use
symmetry; the key_fn cost would need its own stage).
"""

from __future__ import annotations

import time
from typing import Dict

import jax
import jax.numpy as jnp

from ..core.batch import BatchableModel
from ..ops.fingerprint import fingerprint_state
from ..ops.hashset import (
    hashset_insert,
    hashset_insert_unsorted,
    hashset_new,
)

_U32_MAX = jnp.uint32(0xFFFFFFFF)

# Chip peaks for roofline attainment, keyed on jax Device.device_kind.
# v5e: 197 bf16 TFLOP/s, 819 GB/s HBM (public spec sheet). The BFS is
# integer/memory-bound, so HBM attainment is the meaningful axis; the
# FLOP figure is reported for completeness only.
DEVICE_PEAKS = {
    "TPU v5 lite": {"hbm_gbps": 819.0, "bf16_tflops": 197.0},
    "TPU v5": {"hbm_gbps": 1228.0, "bf16_tflops": 459.0},
    "TPU v4": {"hbm_gbps": 1200.0, "bf16_tflops": 275.0},
}


def _memory_traffic(compiled) -> float:
    """Post-fusion HBM traffic estimate of one call: arguments read +
    outputs written + temp buffers written-then-read. This is what the
    chip's HBM actually moves — XLA's op-level ``bytes accessed``
    (``_cost``) counts every pre-fusion elementwise op as a full
    round-trip, overstating fused compute chains by an order of
    magnitude, which round 4's roofline math inherited."""
    try:
        ma = compiled.memory_analysis()
        return float(
            ma.argument_size_in_bytes
            + ma.output_size_in_bytes
            + 2 * ma.temp_size_in_bytes
        )
    except Exception:
        return 0.0


def _cost(compiled) -> Dict[str, float]:
    """FLOPs + bytes from a compiled executable's cost analysis (best
    effort: some backends return None or a list)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes": float(ca.get("bytes accessed", 0.0)),
    }


def _time_stage(fn, args, iters: int) -> float:
    """Median-of-iters seconds for one blocked stage call."""
    out = fn(*args)
    jax.block_until_ready(out)  # warmup/compile
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


def measure_wave_breakdown(
    model: BatchableModel,
    frontier_capacity: int = 1 << 11,
    table_capacity: int = 1 << 20,
    warmup_waves: int = 6,
    iters: int = 20,
    wave_dedup: str | None = None,
) -> Dict:
    """Stage-split timings + cost analysis on a representative wave.

    Runs the staged pipeline for ``warmup_waves`` real waves from the
    model's initial states (so the measured frontier holds real states at
    a realistic fill), then times each stage. Returns a dict of
    per-stage seconds, the fused-wave seconds, per-wave cost-analysis
    totals, and roofline attainment when the device peak is known.

    ``wave_dedup`` must match the configuration being attributed
    (``TpuBfsChecker``'s knob): "sort" measures the sort_dedup + sorted
    insert stages; "scatter" replaces both with the single
    duplicate-tolerant ``insert`` stage the scatter path actually runs —
    attributing a sort the measured rate never executes would mislead
    the next optimization round. None resolves to the same backend
    default the checker uses (``default_wave_dedup``).
    """
    if wave_dedup is None:
        from .tpu import default_wave_dedup

        wave_dedup = default_wave_dedup(jax.default_backend())
    if wave_dedup not in ("sort", "scatter"):
        raise ValueError(f"wave_dedup must be 'sort' or 'scatter': {wave_dedup!r}")
    F = 1 << (frontier_capacity - 1).bit_length()
    A = model.packed_action_count()
    B = F * A
    conditions = model.packed_conditions()
    fp_fn = model.packed_fingerprint
    # Attribute the pipeline the checker actually runs: models providing
    # the fps hooks get the fingerprint-only wave (expand_fps / insert /
    # materialize), everything else the materializing wave.
    use_fps = (
        type(model).packed_expand_fps is not BatchableModel.packed_expand_fps
        and type(model).packed_take is not BatchableModel.packed_take
    )

    def expand(states, mask):
        cand, cvalid = jax.vmap(model.packed_expand)(states)
        cvalid = cvalid & mask[:, None]
        cvalid = cvalid & jax.vmap(jax.vmap(model.packed_within_boundary))(cand)
        return cand, cvalid

    def props(states, mask):
        if not conditions:
            return jnp.zeros((1,), bool)
        return jnp.stack([jax.vmap(c)(states) & mask for c in conditions])

    def fingerprint(cand):
        flat = jax.tree_util.tree_map(
            lambda x: x.reshape((B,) + x.shape[2:]), cand
        )
        return jax.vmap(fp_fn)(flat)

    def sort_dedup(chi, clo, flat_valid):
        shi = jnp.where(flat_valid, chi, _U32_MAX)
        slo = jnp.where(flat_valid, clo, _U32_MAX)
        shi, slo, sidx = jax.lax.sort(
            (shi, slo, jnp.arange(B, dtype=jnp.int32)), num_keys=2
        )
        uniq = jnp.concatenate(
            [jnp.ones((1,), bool), (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])]
        )
        return shi, slo, sidx, flat_valid[sidx] & uniq

    def insert(table, shi, slo, active):
        return hashset_insert(table, shi, slo, active)

    def insert_scatter(table, chi, clo, flat_valid):
        return hashset_insert_unsorted(table, chi, clo, flat_valid)

    def compact_refs(fresh, sidx):
        """F-compacted source references of the fresh lanes — the wave's
        next-frontier selection (beyond-F fresh lanes go to later
        segments/chunks in the real checker). Shared slot math for both
        pipelines."""
        pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        out_slot = jnp.where(fresh & (pos < F), pos, F)
        src_idx = jnp.zeros((F,), jnp.int32).at[out_slot].set(
            sidx, mode="drop"
        )
        taken = jnp.zeros((F,), bool).at[out_slot].set(fresh, mode="drop")
        return src_idx, taken

    def compact(cand, sidx, fresh):
        flat = jax.tree_util.tree_map(
            lambda x: x.reshape((B,) + x.shape[2:]), cand
        )
        src_idx, taken = compact_refs(fresh, sidx)
        new_states = jax.tree_util.tree_map(lambda x: x[src_idx], flat)
        return new_states, taken

    def expand_fps(states, mask):
        hi, lo, v = jax.vmap(model.packed_expand_fps)(states)
        v = v & mask[:, None]
        return hi.reshape(B), lo.reshape(B), v.reshape(B)

    def sort_dedup_flat(chi, clo, flat_valid):
        shi = jnp.where(flat_valid, chi, _U32_MAX)
        slo = jnp.where(flat_valid, clo, _U32_MAX)
        shi, slo, sidx = jax.lax.sort(
            (shi, slo, jnp.arange(B, dtype=jnp.int32)), num_keys=2
        )
        uniq = jnp.concatenate(
            [jnp.ones((1,), bool), (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])]
        )
        return shi, slo, sidx, flat_valid[sidx] & uniq

    def insert_scatter_flat(table, chi, clo, flat_valid):
        return hashset_insert_unsorted(table, chi, clo, flat_valid)

    def fps_compact_refs(fresh, sidx):
        """F-compacted (parent, action) references of the fresh lanes —
        the wave's next-frontier selection (beyond-F fresh lanes go to
        later segments/chunks in the real checker)."""
        pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        out_slot = jnp.where(fresh & (pos < F), pos, F)
        src_idx = jnp.zeros((F,), jnp.int32).at[out_slot].set(
            sidx, mode="drop"
        )
        taken = jnp.zeros((F,), bool).at[out_slot].set(fresh, mode="drop")
        return src_idx, taken

    def materialize(states, src_idx):
        """One F-lane segment of fresh-child materialization (the real
        pipeline runs ceil(n_new / F) of these per wave)."""
        parents = jax.tree_util.tree_map(lambda x: x[src_idx // A], states)
        return jax.vmap(model.packed_take)(parents, src_idx % A)

    def fused(table, states, mask):
        # The props result is returned (not dropped) so XLA cannot
        # dead-code-eliminate the predicate out of the fused timing.
        pv = props(states, mask)
        if use_fps:
            chi, clo, cvalid = expand_fps(states, mask)
            if wave_dedup == "scatter":
                table, fresh, _found, _pending = insert_scatter_flat(
                    table, chi, clo, cvalid
                )
                sidx = jnp.arange(B, dtype=jnp.int32)
            else:
                shi, slo, sidx, active = sort_dedup_flat(chi, clo, cvalid)
                table, fresh, _found, _pending = insert(
                    table, shi, slo, active
                )
            src_idx, taken = fps_compact_refs(fresh, sidx)
            new_states = materialize(states, src_idx)
            return table, new_states, taken, pv.any()
        cand, cvalid = expand(states, mask)
        cvalid = cvalid.reshape(B)  # (F, A) grid -> flat lanes, like _wave
        chi, clo = fingerprint(cand)
        if wave_dedup == "scatter":
            table, fresh, _found, _pending = insert_scatter(
                table, chi, clo, cvalid
            )
            sidx = jnp.arange(B, dtype=jnp.int32)
        else:
            shi, slo, sidx, active = sort_dedup(chi, clo, cvalid)
            table, fresh, _found, _pending = insert(table, shi, slo, active)
        new_states, taken = compact(cand, sidx, fresh)
        return table, new_states, taken, pv.any()

    j_expand = jax.jit(expand)
    j_props = jax.jit(props)
    j_fp = jax.jit(fingerprint)
    j_sort = jax.jit(sort_dedup)
    j_insert = jax.jit(insert)
    j_insert_scatter = jax.jit(insert_scatter)
    j_compact = jax.jit(compact)
    j_fused = jax.jit(fused)
    j_expand_fps = jax.jit(expand_fps)
    j_sort_flat = jax.jit(sort_dedup_flat)
    j_insert_scatter_flat = jax.jit(insert_scatter_flat)
    j_materialize = jax.jit(materialize)
    j_refs = jax.jit(fps_compact_refs)

    # Seed: initial states padded to the frontier width.
    init = model.packed_init_states()
    n0 = min(jax.tree_util.tree_leaves(init)[0].shape[0], F)
    states = jax.tree_util.tree_map(
        lambda x: jnp.zeros((F,) + x.shape[1:], x.dtype).at[:n0].set(x[:F]),
        init,
    )
    mask = jnp.arange(F) < n0
    table = hashset_new(table_capacity)
    # Claim the init states so wave 1 doesn't re-find them.
    ihi, ilo = jax.vmap(fp_fn)(states)
    shi0, slo0, _ = jax.lax.sort(
        (jnp.where(mask, ihi, _U32_MAX), jnp.where(mask, ilo, _U32_MAX),
         jnp.arange(F, dtype=jnp.int32)),
        num_keys=2,
    )
    uniq0 = jnp.concatenate(
        [jnp.ones((1,), bool), (shi0[1:] != shi0[:-1]) | (slo0[1:] != slo0[:-1])]
    )
    table, _, _, _ = hashset_insert(
        table, shi0, slo0, (jnp.arange(F) < n0) & uniq0
    )

    for _ in range(warmup_waves):
        nxt = j_fused(table, states, mask)
        if not bool(nxt[2].any()):
            break  # space exhausted; measure on the last non-empty wave
        table, states, mask = nxt[0], nxt[1], nxt[2]

    frontier_fill = float(mask.sum()) / F
    materialize_segments = None
    if use_fps:
        fhi, flo, fvalid = j_expand_fps(states, mask)
        stages = {
            "expand_fps": (j_expand_fps, (states, mask)),
            "properties": (j_props, (states, mask)),
        }
        if wave_dedup == "scatter":
            _, fresh_f, _, _ = j_insert_scatter_flat(table, fhi, flo, fvalid)
            sidx_f = jnp.arange(B, dtype=jnp.int32)
            stages["insert"] = (
                j_insert_scatter_flat,
                (table, fhi, flo, fvalid),
            )
        else:
            shi, slo, sidx_f, active_f = j_sort_flat(fhi, flo, fvalid)
            fresh_f = active_f
            stages["sort_dedup"] = (j_sort_flat, (fhi, flo, fvalid))
            stages["insert"] = (j_insert, (table, shi, slo, active_f))
        src_idx_f, _ = j_refs(fresh_f, sidx_f)
        n_new_rep = int(fresh_f.sum())
        # The checker materializes fresh lanes in F-wide segments; the
        # timed stage is ONE segment, and the per-wave totals scale by the
        # representative wave's segment count.
        materialize_segments = max(1, -(-n_new_rep // F))
        stages["materialize"] = (j_materialize, (states, src_idx_f))
    else:
        cand, cvalid = j_expand(states, mask)
        cvalid = cvalid.reshape(B)  # flat lanes, matching the fused wave
        chi, clo = j_fp(cand)

        stages = {
            "expand": (j_expand, (states, mask)),
            "properties": (j_props, (states, mask)),
            "fingerprint": (j_fp, (cand,)),
        }
        if wave_dedup == "scatter":
            _, fresh_sc, _, _ = j_insert_scatter(table, chi, clo, cvalid)
            stages["insert"] = (j_insert_scatter, (table, chi, clo, cvalid))
            stages["compact"] = (
                j_compact,
                (cand, jnp.arange(B, dtype=jnp.int32), fresh_sc),
            )
        else:
            shi, slo, sidx, active = j_sort(chi, clo, cvalid)
            stages["sort_dedup"] = (j_sort, (chi, clo, cvalid))
            stages["insert"] = (j_insert, (table, shi, slo, active))
            stages["compact"] = (j_compact, (cand, sidx, active))
    out = {
        "frontier_capacity": F,
        "action_count": A,
        "frontier_fill": round(frontier_fill, 4),
        "device": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "wave_dedup": wave_dedup,
        "stages_ms": {},
        "stage_cost": {},
    }
    total_bytes = 0.0
    total_flops = 0.0
    if materialize_segments is not None:
        # materialize stage numbers are per F-lane segment; totals below
        # scale them by this count (the representative wave's real cost).
        out["materialize_segments_per_wave"] = materialize_segments
        out["pipeline"] = "fps"
    for name, (fn, args) in stages.items():
        scale = (
            materialize_segments
            if name == "materialize" and materialize_segments
            else 1
        )
        out["stages_ms"][name] = round(
            _time_stage(fn, args, iters) * 1e3 * scale, 4
        )
        cost = _cost(fn.lower(*args).compile())
        if cost:
            cost = {k: v * scale for k, v in cost.items()}
            out["stage_cost"][name] = cost
            total_bytes += cost["bytes"]
            total_flops += cost["flops"]
    out["fused_wave_ms"] = round(
        _time_stage(j_fused, (table, states, mask), iters) * 1e3, 4
    )
    fused_compiled = j_fused.lower(table, states, mask).compile()
    fused_traffic = _memory_traffic(fused_compiled)

    # Normalize: candidates processed per wave is the honest denominator
    # for "bytes per state" (every candidate is fingerprinted/sorted
    # whether or not it turns out fresh).
    out["candidates_per_wave"] = B
    if total_bytes:
        # Op-level (pre-fusion) accounting: an upper bound that charges
        # every elementwise op a full memory round-trip.
        out["bytes_per_candidate"] = round(total_bytes / B, 1)
        out["flops_per_candidate"] = round(total_flops / B, 1)
    if fused_traffic:
        # Post-fusion buffer traffic of the ONE fused executable the
        # checker actually runs per wave — the honest HBM figure for
        # roofline math (BASELINE.md north-star feasibility).
        out["hbm_bytes_per_candidate"] = round(fused_traffic / B, 1)
        out["fused_wave_hbm_bytes"] = fused_traffic
    kind = out["device_kind"]
    peak = DEVICE_PEAKS.get(kind) or next(
        (v for k, v in DEVICE_PEAKS.items() if kind.startswith(k)), None
    )
    if peak and (fused_traffic or total_bytes):
        # Roofline: the time HBM alone would need for the wave's traffic,
        # over the measured fused time. Post-fusion traffic when the
        # backend reports it (op-level bytes otherwise). Low attainment =
        # dispatch/latency bound (small waves) or compute-bound stages.
        ideal_s = (fused_traffic or total_bytes) / (peak["hbm_gbps"] * 1e9)
        out["hbm_peak_gbps"] = peak["hbm_gbps"]
        out["hbm_roofline_attainment"] = round(
            ideal_s / (out["fused_wave_ms"] / 1e3), 4
        )
    return out
