"""Per-wave cost attribution for the device BFS — bench instrumentation.

``TpuBfsChecker._wave`` is one fused jit on purpose (host round trips
through the device tunnel cost ~0.1-1s); a fused kernel cannot say where
wave time goes. This module mirrors the wave pipeline as SEPARATELY
jitted stages — expand / properties / fingerprint / sort-dedup / insert /
compact — drives a few real waves to reach a representative frontier,
then times each stage with ``block_until_ready`` and pulls XLA's compiled
``cost_analysis`` (FLOPs, bytes accessed) per stage. The stage split adds
dispatch overhead the fused wave does not pay, so the fused wave is timed
too and reported alongside (stage sums exceeding the fused time = the
overhead, not a lie).

Occupancy-adaptive dispatch is mirrored as well: the representative
frontier's live lanes are counted, compacted to a dense prefix, and the
wave is attributed at the smallest ladder bucket holding them — the exact
dispatch the checker runs. ``fused_wave_ms`` is therefore the bucketed
wave; ``fused_wave_fixed_ms`` keeps the fixed-F_max figure the pre-bucket
rounds reported (their ratio is the dispatch win), ``bucket_fused_ms``
times every ladder rung, and ``compact_ms`` prices the compaction pass
the bucketed dispatch adds.

The output feeds ``bench.py``'s breakdown fields: per-stage milliseconds,
bytes-per-state, and a roofline attainment figure against the chip's HBM
peak — the judgeability half of VERDICT r03 #1. The reference's analog is
its ``ReportData`` throughput surface (``/root/reference/src/report.rs:
10-98``), which has no per-phase attribution at all.

Symmetry-reduced models are not supported (none of the bench legs use
symmetry; the key_fn cost would need its own stage).
"""

from __future__ import annotations

import time
from typing import Dict

import jax
import jax.numpy as jnp

from ..core.batch import BatchableModel
from ..ops.fingerprint import fingerprint_state
from ..ops.hashset import (
    hashset_insert,
    hashset_insert_unsorted,
    hashset_new,
)

_U32_MAX = jnp.uint32(0xFFFFFFFF)

# Chip peaks for roofline attainment, keyed on jax Device.device_kind.
# v5e: 197 bf16 TFLOP/s, 819 GB/s HBM (public spec sheet). The BFS is
# integer/memory-bound, so HBM attainment is the meaningful axis; the
# FLOP figure is reported for completeness only.
DEVICE_PEAKS = {
    "TPU v5 lite": {"hbm_gbps": 819.0, "bf16_tflops": 197.0},
    "TPU v5": {"hbm_gbps": 1228.0, "bf16_tflops": 459.0},
    "TPU v4": {"hbm_gbps": 1200.0, "bf16_tflops": 275.0},
}


def _memory_traffic(compiled) -> float:
    """Post-fusion HBM traffic estimate of one call: arguments read +
    outputs written + temp buffers written-then-read. This is what the
    chip's HBM actually moves — XLA's op-level ``bytes accessed``
    (``_cost``) counts every pre-fusion elementwise op as a full
    round-trip, overstating fused compute chains by an order of
    magnitude, which round 4's roofline math inherited."""
    try:
        ma = compiled.memory_analysis()
        return float(
            ma.argument_size_in_bytes
            + ma.output_size_in_bytes
            + 2 * ma.temp_size_in_bytes
        )
    except Exception:
        return 0.0


def _cost(compiled) -> Dict[str, float]:
    """FLOPs + bytes from a compiled executable's cost analysis (best
    effort: some backends return None or a list)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes": float(ca.get("bytes accessed", 0.0)),
    }


def _time_stage(fn, args, iters: int) -> float:
    """Median-of-iters seconds for one blocked stage call."""
    out = fn(*args)
    jax.block_until_ready(out)  # warmup/compile
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


def measure_wave_breakdown(
    model: BatchableModel,
    frontier_capacity: int = 1 << 11,
    table_capacity: int = 1 << 20,
    warmup_waves: int = 6,
    iters: int = 20,
    wave_dedup: str | None = None,
    bucket_ladder: int | None = None,
    wave_kernel: str = "staged",
) -> Dict:
    """Stage-split timings + cost analysis on a representative wave.

    Runs the staged pipeline for ``warmup_waves`` real waves from the
    model's initial states (so the measured frontier holds real states at
    a realistic fill), compacts the live lanes and selects the ladder
    bucket exactly like the checker's dispatch, then times each stage at
    that bucket. Returns a dict of per-stage seconds, the bucketed and
    fixed-width fused-wave seconds, per-rung fused times, per-wave
    cost-analysis totals, and roofline attainment when the device peak is
    known.

    ``wave_dedup`` must match the configuration being attributed
    (``TpuBfsChecker``'s knob): "sort" measures the sort_dedup + sorted
    insert stages; "scatter" replaces both with the single
    duplicate-tolerant ``insert`` stage the scatter path actually runs —
    attributing a sort the measured rate never executes would mislead
    the next optimization round. None resolves to the same backend
    default the checker uses (``default_wave_dedup``). ``bucket_ladder``
    mirrors the checker knob (None = the default ladder, 0 = fixed
    width).

    ``wave_kernel="fused"`` attributes the Pallas wave megakernel
    (``ops/pallas_wave.py``) instead of the staged stage split: the
    whole wave is ONE dispatch, so ``stages_ms`` holds a single
    ``wave_kernel`` entry and ``dispatches_per_wave`` drops to 1 (the
    staged split reports its stage count there — the dispatch-overhead
    collapse the megakernel buys, rendered by ``bench.py
    --megakernel``). The fused kernel fixes the sorted-dedup
    discipline, so ``wave_dedup="scatter"`` is rejected, and
    ``table_capacity`` is tile-rounded like the checker does.
    """
    from .tpu import (
        _AUTO_BUCKET_MIN_F,
        _DEFAULT_BUCKET_STEPS,
        bucket_for,
        bucket_ladder_widths,
        default_wave_dedup,
    )

    if wave_kernel not in ("staged", "fused"):
        raise ValueError(
            f"wave_kernel must be 'staged' or 'fused': {wave_kernel!r}"
        )
    if wave_kernel == "fused":
        if wave_dedup == "scatter":
            raise ValueError(
                "wave_kernel='fused' fixes the sorted-dedup discipline; "
                "attribute wave_dedup='scatter' with wave_kernel='staged'"
            )
        wave_dedup = "sort"
        from ..ops.pallas_hashset import round_table_capacity

        table_capacity = round_table_capacity(table_capacity)
    if wave_dedup is None:
        wave_dedup = default_wave_dedup(jax.default_backend())
    if wave_dedup not in ("sort", "scatter"):
        raise ValueError(f"wave_dedup must be 'sort' or 'scatter': {wave_dedup!r}")
    F = 1 << (frontier_capacity - 1).bit_length()
    if bucket_ladder is None:
        # Mirror the checker's auto rule so the attributed dispatch is
        # the dispatched dispatch.
        bucket_ladder = (
            _DEFAULT_BUCKET_STEPS if F >= _AUTO_BUCKET_MIN_F else 0
        )
    ladder = bucket_ladder_widths(F, bucket_ladder)
    A = model.packed_action_count()
    conditions = model.packed_conditions()
    fp_fn = model.packed_fingerprint
    # Attribute the pipeline the checker actually runs: models providing
    # the fps hooks get the fingerprint-only wave (expand_fps / insert /
    # materialize), everything else the materializing wave. Every stage
    # below is shape-polymorphic in the frontier width (widths are taken
    # from the inputs), so one definition serves every ladder rung.
    use_fps = (
        type(model).packed_expand_fps is not BatchableModel.packed_expand_fps
        and type(model).packed_take is not BatchableModel.packed_take
    )
    if wave_kernel == "fused":
        # The fused megakernel materializes the candidate grid in VMEM
        # scratch — the checker refuses expand_fps under it; mirror.
        use_fps = False

    def expand(states, mask):
        cand, cvalid = jax.vmap(model.packed_expand)(states)
        cvalid = cvalid & mask[:, None]
        cvalid = cvalid & jax.vmap(jax.vmap(model.packed_within_boundary))(cand)
        return cand, cvalid

    def props(states, mask):
        if not conditions:
            return jnp.zeros((1,), bool)
        return jnp.stack([jax.vmap(c)(states) & mask for c in conditions])

    def fingerprint(cand):
        flat = jax.tree_util.tree_map(
            lambda x: x.reshape((-1,) + x.shape[2:]), cand
        )
        return jax.vmap(fp_fn)(flat)

    def sort_dedup(chi, clo, flat_valid):
        b = chi.shape[0]
        shi = jnp.where(flat_valid, chi, _U32_MAX)
        slo = jnp.where(flat_valid, clo, _U32_MAX)
        shi, slo, sidx = jax.lax.sort(
            (shi, slo, jnp.arange(b, dtype=jnp.int32)), num_keys=2
        )
        uniq = jnp.concatenate(
            [jnp.ones((1,), bool), (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])]
        )
        return shi, slo, sidx, flat_valid[sidx] & uniq

    def insert(table, shi, slo, active):
        return hashset_insert(table, shi, slo, active)

    def insert_scatter(table, chi, clo, flat_valid):
        return hashset_insert_unsorted(table, chi, clo, flat_valid)

    def compact_refs(fresh, sidx):
        """Width-compacted source references of the fresh lanes — the
        wave's next-frontier selection (beyond-width fresh lanes go to
        later segments/chunks in the real checker). Shared slot math for
        both pipelines."""
        b = fresh.shape[0]
        f_out = b // A
        pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        out_slot = jnp.where(fresh & (pos < f_out), pos, f_out)
        src_idx = jnp.zeros((f_out,), jnp.int32).at[out_slot].set(
            sidx, mode="drop"
        )
        taken = jnp.zeros((f_out,), bool).at[out_slot].set(fresh, mode="drop")
        return src_idx, taken

    def compact(cand, sidx, fresh):
        flat = jax.tree_util.tree_map(
            lambda x: x.reshape((-1,) + x.shape[2:]), cand
        )
        src_idx, taken = compact_refs(fresh, sidx)
        new_states = jax.tree_util.tree_map(lambda x: x[src_idx], flat)
        return new_states, taken

    def expand_fps(states, mask):
        hi, lo, v = jax.vmap(model.packed_expand_fps)(states)
        v = v & mask[:, None]
        return hi.reshape(-1), lo.reshape(-1), v.reshape(-1)

    def materialize(states, src_idx):
        """One frontier-width segment of fresh-child materialization (the
        real pipeline runs ceil(n_new / width) of these per wave)."""
        parents = jax.tree_util.tree_map(lambda x: x[src_idx // A], states)
        return jax.vmap(model.packed_take)(parents, src_idx % A)

    def compact_dispatch(states, mask):
        """The checker's pre-dispatch live-lane compaction (_compact_chunk):
        a stable cumsum scatter of the frontier rows to a dense prefix —
        the overhead the bucketed dispatch adds over fixed width."""
        f_in = mask.shape[0]
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        dest = jnp.where(mask, pos, f_in)

        def scat(x):
            z = jnp.zeros((f_in,) + x.shape[1:], x.dtype)
            return z.at[dest].set(x, mode="drop")

        out = jax.tree_util.tree_map(scat, states)
        new_mask = jnp.arange(f_in, dtype=jnp.int32) < mask.sum(
            dtype=jnp.int32
        )
        return out, new_mask

    def fused(table, states, mask):
        # The props result is returned (not dropped) so XLA cannot
        # dead-code-eliminate the predicate out of the fused timing.
        b = mask.shape[0] * A
        pv = props(states, mask)
        if use_fps:
            chi, clo, cvalid = expand_fps(states, mask)
            if wave_dedup == "scatter":
                table, fresh, _found, _pending = insert_scatter(
                    table, chi, clo, cvalid
                )
                sidx = jnp.arange(b, dtype=jnp.int32)
            else:
                shi, slo, sidx, active = sort_dedup(chi, clo, cvalid)
                table, fresh, _found, _pending = insert(
                    table, shi, slo, active
                )
            src_idx, taken = compact_refs(fresh, sidx)
            new_states = materialize(states, src_idx)
            return table, new_states, taken, pv.any()
        cand, cvalid = expand(states, mask)
        cvalid = cvalid.reshape(b)  # (F, A) grid -> flat lanes, like _wave
        chi, clo = fingerprint(cand)
        if wave_dedup == "scatter":
            table, fresh, _found, _pending = insert_scatter(
                table, chi, clo, cvalid
            )
            sidx = jnp.arange(b, dtype=jnp.int32)
        else:
            shi, slo, sidx, active = sort_dedup(chi, clo, cvalid)
            table, fresh, _found, _pending = insert(table, shi, slo, active)
        new_states, taken = compact(cand, sidx, fresh)
        return table, new_states, taken, pv.any()

    j_expand = jax.jit(expand)
    j_props = jax.jit(props)
    j_fp = jax.jit(fingerprint)
    j_sort = jax.jit(sort_dedup)
    j_insert = jax.jit(insert)
    j_insert_scatter = jax.jit(insert_scatter)
    j_compact = jax.jit(compact)
    j_fused = jax.jit(fused)
    j_expand_fps = jax.jit(expand_fps)
    j_materialize = jax.jit(materialize)
    j_refs = jax.jit(compact_refs)
    j_compact_dispatch = jax.jit(compact_dispatch)

    # Seed: initial states padded to the frontier width.
    init = model.packed_init_states()
    n0 = min(jax.tree_util.tree_leaves(init)[0].shape[0], F)
    states = jax.tree_util.tree_map(
        lambda x: jnp.zeros((F,) + x.shape[1:], x.dtype).at[:n0].set(x[:F]),
        init,
    )
    mask = jnp.arange(F) < n0
    table = hashset_new(table_capacity)
    # Claim the init states so wave 1 doesn't re-find them.
    ihi, ilo = jax.vmap(fp_fn)(states)
    shi0, slo0, _ = jax.lax.sort(
        (jnp.where(mask, ihi, _U32_MAX), jnp.where(mask, ilo, _U32_MAX),
         jnp.arange(F, dtype=jnp.int32)),
        num_keys=2,
    )
    uniq0 = jnp.concatenate(
        [jnp.ones((1,), bool), (shi0[1:] != shi0[:-1]) | (slo0[1:] != slo0[:-1])]
    )
    table, _, _, _ = hashset_insert(
        table, shi0, slo0, (jnp.arange(F) < n0) & uniq0
    )

    for _ in range(warmup_waves):
        nxt = j_fused(table, states, mask)
        if not bool(nxt[2].any()):
            break  # space exhausted; measure on the last non-empty wave
        table, states, mask = nxt[0], nxt[1], nxt[2]

    live = int(mask.sum())
    frontier_fill = live / F
    # The checker's dispatch: compact live lanes to a dense prefix, pick
    # the smallest ladder bucket that holds them, slice the frontier to it.
    bucket = bucket_for(ladder, max(1, live))
    c_states, c_mask = j_compact_dispatch(states, mask)
    states_w = jax.tree_util.tree_map(lambda x: x[:bucket], c_states)
    mask_w = c_mask[:bucket]
    B = bucket * A

    materialize_segments = None
    if use_fps:
        fhi, flo, fvalid = j_expand_fps(states_w, mask_w)
        stages = {
            "expand_fps": (j_expand_fps, (states_w, mask_w)),
            "properties": (j_props, (states_w, mask_w)),
        }
        if wave_dedup == "scatter":
            _, fresh_f, _, _ = j_insert_scatter(table, fhi, flo, fvalid)
            sidx_f = jnp.arange(B, dtype=jnp.int32)
            stages["insert"] = (
                j_insert_scatter,
                (table, fhi, flo, fvalid),
            )
        else:
            shi, slo, sidx_f, active_f = j_sort(fhi, flo, fvalid)
            fresh_f = active_f
            stages["sort_dedup"] = (j_sort, (fhi, flo, fvalid))
            stages["insert"] = (j_insert, (table, shi, slo, active_f))
        src_idx_f, _ = j_refs(fresh_f, sidx_f)
        n_new_rep = int(fresh_f.sum())
        # The checker materializes fresh lanes in frontier-width segments;
        # the timed stage is ONE segment, and the per-wave totals scale by
        # the representative wave's segment count.
        materialize_segments = max(1, -(-n_new_rep // bucket))
        stages["materialize"] = (j_materialize, (states_w, src_idx_f))
    else:
        cand, cvalid = j_expand(states_w, mask_w)
        cvalid = cvalid.reshape(B)  # flat lanes, matching the fused wave
        chi, clo = j_fp(cand)

        stages = {
            "expand": (j_expand, (states_w, mask_w)),
            "properties": (j_props, (states_w, mask_w)),
            "fingerprint": (j_fp, (cand,)),
        }
        if wave_dedup == "scatter":
            _, fresh_sc, _, _ = j_insert_scatter(table, chi, clo, cvalid)
            stages["insert"] = (j_insert_scatter, (table, chi, clo, cvalid))
            stages["compact"] = (
                j_compact,
                (cand, jnp.arange(B, dtype=jnp.int32), fresh_sc),
            )
        else:
            shi, slo, sidx, active = j_sort(chi, clo, cvalid)
            stages["sort_dedup"] = (j_sort, (chi, clo, cvalid))
            stages["insert"] = (j_insert, (table, shi, slo, active))
            stages["compact"] = (j_compact, (cand, sidx, active))
    staged_dispatches = len(stages)
    if wave_kernel == "fused":
        # The whole wave is ONE Pallas dispatch: replace the stage table
        # with the single wave_kernel stage the checker actually runs.
        from ..ops.pallas_wave import FusedWaveSpec, fused_wave

        props_list = list(model.properties())
        if len(conditions) != len(props_list):
            raise ValueError(
                "packed_conditions() must align 1:1 with properties(): "
                f"{len(conditions)} != {len(props_list)}"
            )
        eventually = [
            i
            for i, p in enumerate(props_list)
            if getattr(p.expectation, "value", None) == "eventually"
        ]
        ebit = tuple((pi, b) for b, pi in enumerate(eventually))
        spec = FusedWaveSpec(
            expand=model.packed_expand,
            within_boundary=model.packed_within_boundary,
            fp_fn=fp_fn,
            conditions=tuple(conditions),
            expectations=tuple(
                p.expectation.value for p in props_list
            ),
            ebit=ebit,
            action_count=A,
            interpret=jax.default_backend() != "tpu",
        )
        hi_w, lo_w = jax.vmap(fp_fn)(states_w)
        ebits_w = jnp.full(
            (bucket,), sum(1 << b for _pi, b in ebit), jnp.uint32
        )
        depth_w = jnp.zeros((bucket,), jnp.int32)

        def mega(table, states, hi, lo, ebits, depth, mask):
            return fused_wave(
                spec, table, states, hi, lo, ebits, depth, mask,
                jnp.int32(2**31 - 1),
            )

        j_mega = jax.jit(mega)
        stages = {
            "wave_kernel": (
                j_mega,
                (table, states_w, hi_w, lo_w, ebits_w, depth_w, mask_w),
            )
        }
    out = {
        "frontier_capacity": F,
        "action_count": A,
        "frontier_fill": round(frontier_fill, 4),
        "live_lanes": live,
        "bucket": bucket,
        "bucket_ladder": ladder,
        "compaction_ratio": round(live / bucket, 4) if bucket else 0.0,
        "device": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "wave_dedup": wave_dedup,
        "wave_kernel": wave_kernel,
        # Kernel launches one wave pays: the staged split's stage count
        # vs the megakernel's single dispatch — the overhead collapse
        # bench.py --megakernel renders.
        "dispatches_per_wave": (
            1 if wave_kernel == "fused" else staged_dispatches
        ),
        "table_capacity": table_capacity,
        "stages_ms": {},
        "stage_cost": {},
    }
    total_bytes = 0.0
    total_flops = 0.0
    if materialize_segments is not None:
        # materialize stage numbers are per bucket-wide segment; totals
        # below scale them by this count (the representative wave's real
        # cost).
        out["materialize_segments_per_wave"] = materialize_segments
        out["pipeline"] = "fps"
    for name, (fn, args) in stages.items():
        scale = (
            materialize_segments
            if name == "materialize" and materialize_segments
            else 1
        )
        out["stages_ms"][name] = round(
            _time_stage(fn, args, iters) * 1e3 * scale, 4
        )
        cost = _cost(fn.lower(*args).compile())
        if cost:
            cost = {k: v * scale for k, v in cost.items()}
            out["stage_cost"][name] = cost
            total_bytes += cost["bytes"]
            total_flops += cost["flops"]
    # The compaction pass the bucketed dispatch adds (full-width frontier
    # in, dense prefix out) — the overhead the tier-1 micro-benchmark
    # budget-tests against the fixed-width wave.
    out["compact_ms"] = round(
        _time_stage(j_compact_dispatch, (states, mask), iters) * 1e3, 4
    )
    # THE dispatched wave: fused at the selected bucket (acceptance
    # metric), alongside the fixed-width wave the pre-bucket rounds
    # measured and the full per-rung ladder.
    out["fused_wave_ms"] = round(
        _time_stage(j_fused, (table, states_w, mask_w), iters) * 1e3, 4
    )
    out["fused_wave_fixed_ms"] = round(
        _time_stage(j_fused, (table, states, mask), iters) * 1e3, 4
    )
    bucket_fused = {}
    for w in ladder:
        if w == bucket:
            bucket_fused[str(w)] = out["fused_wave_ms"]
        elif w == F:
            bucket_fused[str(w)] = out["fused_wave_fixed_ms"]
        else:
            bucket_fused[str(w)] = round(
                _time_stage(
                    j_fused,
                    (
                        table,
                        jax.tree_util.tree_map(
                            lambda x: x[:w], c_states
                        ),
                        c_mask[:w],
                    ),
                    iters,
                )
                * 1e3,
                4,
            )
    out["bucket_fused_ms"] = bucket_fused
    fused_compiled = j_fused.lower(table, states_w, mask_w).compile()
    fused_traffic = _memory_traffic(fused_compiled)

    # Normalize: candidates processed per dispatched wave is the honest
    # denominator for "bytes per state" (every candidate lane in the
    # bucket is fingerprinted/sorted whether or not it turns out fresh).
    out["candidates_per_wave"] = B
    out["candidates_per_wave_fixed"] = F * A
    if total_bytes:
        # Op-level (pre-fusion) accounting: an upper bound that charges
        # every elementwise op a full memory round-trip.
        out["bytes_per_candidate"] = round(total_bytes / B, 1)
        out["flops_per_candidate"] = round(total_flops / B, 1)
    if fused_traffic:
        # Post-fusion buffer traffic of the ONE fused executable the
        # checker actually runs per wave — the honest HBM figure for
        # roofline math (BASELINE.md north-star feasibility).
        out["hbm_bytes_per_candidate"] = round(fused_traffic / B, 1)
        out["fused_wave_hbm_bytes"] = fused_traffic
    kind = out["device_kind"]
    peak = DEVICE_PEAKS.get(kind) or next(
        (v for k, v in DEVICE_PEAKS.items() if kind.startswith(k)), None
    )
    if peak and (fused_traffic or total_bytes):
        # Roofline: the time HBM alone would need for the wave's traffic,
        # over the measured fused time. Post-fusion traffic when the
        # backend reports it (op-level bytes otherwise). Low attainment =
        # dispatch/latency bound (small waves) or compute-bound stages.
        ideal_s = (fused_traffic or total_bytes) / (peak["hbm_gbps"] * 1e9)
        out["hbm_peak_gbps"] = peak["hbm_gbps"]
        out["hbm_roofline_attainment"] = round(
            ideal_s / (out["fused_wave_ms"] / 1e3), 4
        )
    return out


def measure_pipeline_choice(
    model: BatchableModel,
    frontier_capacity: int = 1 << 10,
    table_capacity: int = 1 << 16,
    wave_dedup: str | None = None,
    warmup_waves: int = 4,
    iters: int = 5,
) -> Dict:
    """expand_fps as a MEASURED policy: times one calibration wave under
    each expansion pipeline — ``fps`` (fingerprint-only expansion +
    fresh-lane materialization) and ``materialize`` (the full F × A
    candidate grid) — on the same representative frontier, so bench.py
    can compare the configured pipeline against the timed winner instead
    of trusting the auto rule (VERDICT r05: abd3o 2.5× and scr4 26% CPU
    regressions landed silently under auto-fps).

    Returns ``{"supported": False}`` when the model has no fps hooks
    (one pipeline exists; nothing to compare), else ``fps_ms`` /
    ``materialize_ms`` (median-of-iters, compile excluded) and
    ``measured_faster``. Both pipelines run the same dedup/insert
    (``wave_dedup``: the checker's knob, None = backend default), so the
    delta is the expansion strategy itself.
    """
    from .tpu import default_wave_dedup, supports_expand_fps

    out: Dict = {"supported": bool(supports_expand_fps(model))}
    if not out["supported"]:
        return out
    if wave_dedup is None:
        wave_dedup = default_wave_dedup(jax.default_backend())
    if wave_dedup not in ("sort", "scatter"):
        raise ValueError(
            f"wave_dedup must be 'sort' or 'scatter': {wave_dedup!r}"
        )
    F = 1 << (frontier_capacity - 1).bit_length()
    A = model.packed_action_count()
    B = F * A
    fp_fn = model.packed_fingerprint

    def _dedup_insert(table, chi, clo, cvalid):
        if wave_dedup == "scatter":
            table, fresh, _found, _p = hashset_insert_unsorted(
                table, chi, clo, cvalid
            )
            return table, fresh, jnp.arange(B, dtype=jnp.int32)
        shi = jnp.where(cvalid, chi, _U32_MAX)
        slo = jnp.where(cvalid, clo, _U32_MAX)
        shi, slo, sidx = jax.lax.sort(
            (shi, slo, jnp.arange(B, dtype=jnp.int32)), num_keys=2
        )
        uniq = jnp.concatenate(
            [jnp.ones((1,), bool),
             (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])]
        )
        active = cvalid[sidx] & uniq
        table, fresh, _found, _p = hashset_insert(table, shi, slo, active)
        return table, fresh, sidx

    def _next_refs(fresh, sidx):
        pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        out_slot = jnp.where(fresh & (pos < F), pos, F)
        src_idx = jnp.zeros((F,), jnp.int32).at[out_slot].set(
            sidx, mode="drop"
        )
        taken = jnp.zeros((F,), bool).at[out_slot].set(fresh, mode="drop")
        return src_idx, taken

    def mat_wave(table, states, mask):
        cand, cvalid = jax.vmap(model.packed_expand)(states)
        cvalid = cvalid & mask[:, None]
        cvalid = cvalid & jax.vmap(
            jax.vmap(model.packed_within_boundary)
        )(cand)
        flat = jax.tree_util.tree_map(
            lambda x: x.reshape((B,) + x.shape[2:]), cand
        )
        cvalid = cvalid.reshape(B)
        chi, clo = jax.vmap(fp_fn)(flat)
        table, fresh, sidx = _dedup_insert(table, chi, clo, cvalid)
        src_idx, taken = _next_refs(fresh, sidx)
        new_states = jax.tree_util.tree_map(lambda x: x[src_idx], flat)
        return table, new_states, taken

    def fps_wave(table, states, mask):
        chi_g, clo_g, cvalid = jax.vmap(model.packed_expand_fps)(states)
        cvalid = (cvalid & mask[:, None]).reshape(B)
        chi, clo = chi_g.reshape(B), clo_g.reshape(B)
        table, fresh, sidx = _dedup_insert(table, chi, clo, cvalid)
        src_idx, taken = _next_refs(fresh, sidx)
        parents = jax.tree_util.tree_map(
            lambda x: x[src_idx // A], states
        )
        new_states = jax.vmap(model.packed_take)(parents, src_idx % A)
        return table, new_states, taken

    j_mat = jax.jit(mat_wave)
    j_fps = jax.jit(fps_wave)

    # Seed + advance to a representative frontier through the
    # materializing wave (both pipelines then time on the SAME frontier
    # against the SAME table — the comparison is expansion-only).
    init = model.packed_init_states()
    n0 = min(jax.tree_util.tree_leaves(init)[0].shape[0], F)
    states = jax.tree_util.tree_map(
        lambda x: jnp.zeros((F,) + x.shape[1:], x.dtype).at[:n0].set(x[:F]),
        init,
    )
    mask = jnp.arange(F) < n0
    table = hashset_new(table_capacity)
    ihi, ilo = jax.vmap(fp_fn)(states)
    shi0, slo0, _ = jax.lax.sort(
        (jnp.where(mask, ihi, _U32_MAX), jnp.where(mask, ilo, _U32_MAX),
         jnp.arange(F, dtype=jnp.int32)),
        num_keys=2,
    )
    uniq0 = jnp.concatenate(
        [jnp.ones((1,), bool),
         (shi0[1:] != shi0[:-1]) | (slo0[1:] != slo0[:-1])]
    )
    table, _, _, _ = hashset_insert(table, shi0, slo0, mask & uniq0)
    for _ in range(warmup_waves):
        nxt = j_mat(table, states, mask)
        if not bool(nxt[2].any()):
            break
        table, states, mask = nxt

    out["frontier_capacity"] = F
    out["live_lanes"] = int(mask.sum())
    out["wave_dedup"] = wave_dedup
    out["materialize_ms"] = round(
        _time_stage(j_mat, (table, states, mask), iters) * 1e3, 4
    )
    out["fps_ms"] = round(
        _time_stage(j_fps, (table, states, mask), iters) * 1e3, 4
    )
    out["measured_faster"] = (
        "fps" if out["fps_ms"] <= out["materialize_ms"] else "materialize"
    )
    return out
