"""The async wave engine's host-side lane: one FIFO worker thread.

In ``async_pipeline=True`` mode the device checkers dispatch wave N+1
while wave N's host-tier work — the two-phase Bloom+run probe at the
wave exit, L0→L1 eviction absorbs (and the LSM merges/spills they
trigger), and checkpoint serialization — runs here. The tenant-packed
engine (``checker/packed_tenancy.py``) rides the same worker for its
per-tenant-partition probes, parent-log appends, and survivor re-entry:
FIFO is the per-tenant merge fence there too, with the engine draining
before evictions, lane drops, and admissions. The design is a
two-deep pipeline (ScalaBFS-style channel pipelining, PAPERS.md): the
device owns expansion/fingerprint/insert, this thread owns the tiered
store's verdicts, and survivors of a deferred probe re-enter the
frontier one wave late through the shared chunk queue.

Correctness rests on three properties this class enforces:

- **FIFO**: jobs run in submission order on ONE thread, so the tiered
  store sees the exact sequence of probes and evictions the synchronous
  path would issue (a probe submitted before an eviction can never
  observe the evicted keys — the "merge fence").
- **Epoch barriers**: ``drain()`` blocks until every submitted job
  finished, re-raising the first job error. Checkers call it at
  checkpoint, preempt, queue-empty, and run-end boundaries, so every
  externally observable snapshot (payloads, counters read after
  ``join()``) is identical to the synchronous path's.
- **Bounded depth**: ``throttle()`` caps the verdict backlog (the
  "pending-verdict lane set"), so at most ``max_pending`` waves of
  device output buffers are pinned at once.

A job that raises poisons the pipeline: later jobs are skipped (their
inputs may depend on the failed verdict) and the error surfaces as a
typed :class:`PipelinePoisonedError` — carrying the original worker
exception as its ``cause``/``__cause__`` — at the next
``submit``/``throttle``/``drain`` on the checker thread, which routes it
into ``worker_error()`` like any other worker failure. Poisoning never
hangs the teardown path: the worker loop keeps draining (skipping) the
queue, so ``close()`` joins, and every tiered-store mutation runs under
``with`` blocks, so no store lock outlives a dying job
(tests/test_faults.py pins both).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional

from ..utils.faults import fault_point

__all__ = ["HostPipeline", "PipelinePoisonedError"]


class PipelinePoisonedError(RuntimeError):
    """The async host pipeline is poisoned: a worker job raised, so no
    further host-tier work can be applied. ``cause`` (also
    ``__cause__``) is the original worker exception — callers routing
    failures (the service's retry classifier) look through this wrapper
    at the root fault."""

    def __init__(self, cause: BaseException):
        super().__init__(
            "async host pipeline failed; no further host-tier work "
            f"can be applied (worker error: {cause!r})"
        )
        self.cause = cause

# Default pending-verdict depth: the producing wave plus one in-flight
# verdict — the "two-deep" in the two-deep pipeline. Deeper queues pin
# more wave-output buffers without adding overlap (the device is already
# never idle at depth 2).
DEFAULT_MAX_PENDING = 2


class HostPipeline:
    """One daemon worker thread executing host-tier jobs in FIFO order."""

    def __init__(self, name: str = "host-pipeline",
                 max_pending: int = DEFAULT_MAX_PENDING):
        self.max_pending = max(1, max_pending)
        self._cv = threading.Condition()
        self._jobs: deque = deque()
        self._pending = 0
        self._submitted = 0
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    # -- checker-thread surface --------------------------------------------

    def submit(self, fn: Callable[[], None]) -> None:
        """Enqueues one job. Raises the pipeline's poisoning error, if
        any — the checker must not keep producing waves whose verdicts
        can never be applied."""
        with self._cv:
            self._raise_if_poisoned()
            if self._closed:
                raise RuntimeError("host pipeline is closed")
            self._jobs.append(fn)
            self._pending += 1
            self._submitted += 1
            self._cv.notify_all()

    def throttle(self, max_pending: Optional[int] = None) -> None:
        """Blocks until the backlog is within the pipeline depth (the
        bounded pending-verdict lane set)."""
        limit = self.max_pending if max_pending is None else max_pending
        with self._cv:
            self._cv.wait_for(
                lambda: self._pending <= limit or self._error is not None
            )
            self._raise_if_poisoned()

    def drain(self) -> None:
        """Epoch barrier: returns once every submitted job has finished;
        re-raises the first job error on this (the caller's) thread."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._pending == 0 or self._error is not None
            )
            # Poisoned: skipped jobs still drain to zero, but the state
            # they would have produced does not exist — surface it.
            self._raise_if_poisoned()

    def pending(self) -> int:
        with self._cv:
            return self._pending

    @property
    def submitted(self) -> int:
        """Total jobs ever submitted (telemetry/tests)."""
        with self._cv:
            return self._submitted

    def close(self) -> None:
        """Stops the worker after the queue empties. Never raises —
        called from run-end/error paths; surface job errors via
        ``drain()`` first when they matter."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=60.0)

    def _raise_if_poisoned(self) -> None:
        if self._error is not None:
            raise PipelinePoisonedError(self._error) from self._error

    # -- worker thread ------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._jobs and not self._closed:
                    self._cv.wait()
                if not self._jobs:
                    return  # closed and drained
                fn = self._jobs.popleft()
                poisoned = self._error is not None
            try:
                if not poisoned:
                    # Injection seam: a fault here IS a worker death —
                    # the job never runs and the pipeline poisons,
                    # exactly the shape a segfaulting probe or a dying
                    # numpy allocation would produce.
                    fault_point("pipeline.worker")
                    fn()
            except BaseException as e:  # noqa: BLE001 - surfaced at barriers
                with self._cv:
                    if self._error is None:
                        self._error = e
            finally:
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()
