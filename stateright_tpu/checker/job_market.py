"""Work-sharing scheduler for checker worker threads.

A mutex-protected list of job batches plus a condition variable. ``pop`` blocks
until work arrives or every worker is idle (global quiescence, at which point
the market closes so all workers shut down). ``split_and_push`` shares surplus
local work with idle workers. A worker that dies (exception) closes the market
via ``close`` so the remaining workers drain out instead of hanging.

Reference design: ``JobBroker``/``JobMarket`` at
``/root/reference/src/job_market.rs``. In the TPU checker this role is played
by the host<->device frontier scheduler instead (the chunk queue/pool in
``stateright_tpu.checker.tpu`` and ``stateright_tpu.parallel.sharded``).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Generic, List, TypeVar

Job = TypeVar("Job")


class JobBroker(Generic[Job]):
    def __init__(self, thread_count: int):
        self._cond = threading.Condition()
        self._open = True
        self._thread_count = thread_count
        self._open_count = thread_count
        self._job_batches: List[Deque[Job]] = []

    def pop(self) -> Deque[Job]:
        """Pop a batch of jobs; blocks. Empty result means no more jobs are
        coming (market closed)."""
        with self._cond:
            if not self._open:
                return deque()
            while True:
                if self._job_batches:
                    return self._job_batches.pop()
                self._open_count = max(0, self._open_count - 1)
                if self._open_count == 0:
                    # Last running thread: quiescence. Close and wake everyone.
                    self._open = False
                    self._cond.notify_all()
                    return deque()
                self._cond.wait()
                if not self._open:
                    return deque()
                self._open_count += 1

    def push(self, jobs: Deque[Job]) -> None:
        with self._cond:
            if not self._open:
                return
            self._job_batches.append(jobs)
            self._cond.notify()

    def split_and_push(self, jobs: Deque[Job]) -> None:
        """Split local surplus into 1 + min(idle_threads, len) pieces, keeping
        the first piece locally and publishing the rest."""
        with self._cond:
            if not self._open:
                jobs.clear()
                return
            idle = max(0, self._thread_count - self._open_count)
            pieces = 1 + min(idle, len(jobs))
            size = len(jobs) // pieces
            for _ in range(1, pieces):
                if size == 0:
                    continue
                to_share = deque()
                for _ in range(size):
                    to_share.appendleft(jobs.pop())
                self._job_batches.append(to_share)
                self._cond.notify()

    def close(self) -> None:
        """Close the market (worker finished or died): drop all queued work and
        wake all waiting workers so they exit."""
        with self._cond:
            self._open = False
            self._job_batches.clear()
            self._open_count = max(0, self._open_count - 1)
            self._cond.notify_all()

    def is_closed(self) -> bool:
        with self._cond:
            return (
                not self._open
                and not self._job_batches
                and self._open_count == 0
            )
