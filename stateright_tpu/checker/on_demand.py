"""On-demand (lazy) host checker powering the Explorer.

Workers block on a control channel: ``CheckFingerprint(fp)`` targets one
pending state for expansion; ``RunToCompletion`` unblocks fully (turning the
checker into a plain BFS). A forwarder thread fans control messages to all
workers. Visited set stores parent pointers like BFS.

Reference design: ``OnDemandChecker`` at
``/root/reference/src/checker/on_demand.rs``.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..core.fingerprint import Fingerprint, fingerprint
from ..core.model import Expectation
from ..core.path import Path
from ..telemetry import BlockInstruments, get_tracer
from ..telemetry.coverage import BlockCoverage, CoverageLedger
from .base import Checker
from .bfs import reconstruct_path
from .job_market import JobBroker

BLOCK_SIZE = 1500

Job = Tuple[object, Fingerprint, frozenset, int]

_CHECK = "check"
_RUN_TO_COMPLETION = "run"


class OnDemandChecker(Checker):
    def __init__(self, options):
        model = options.model
        self._model = model
        target_state_count = options._target_state_count
        thread_count = max(1, options._thread_count)
        visitor = options._visitor
        properties = model.properties()
        property_count = len(properties)

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        self._count_lock = threading.Lock()
        self._max_depth = 0
        self._generated: Dict[Fingerprint, Optional[Fingerprint]] = {}
        for s in init_states:
            self._generated.setdefault(fingerprint(s), None)
        ebits = frozenset(
            i
            for i, p in enumerate(properties)
            if p.expectation == Expectation.EVENTUALLY
        )
        pending: Deque[Job] = deque(
            (s, fingerprint(s), ebits, 1) for s in init_states
        )
        self._discoveries: Dict[str, Fingerprint] = {}
        # Per-block telemetry (see the matching note in bfs.py).
        self._tracer = get_tracer()
        self._bi = BlockInstruments("on_demand")
        # Always-on coverage ledger (see the matching note in bfs.py) —
        # this is what feeds the Explorer's coverage panel.
        self._cov = CoverageLedger(
            "on_demand", properties, tracer=self._tracer
        )
        self._cov.record_seed(len(self._generated))
        self._job_broker: JobBroker[Job] = JobBroker(thread_count)
        self._job_broker.push(pending)
        self._worker_error: Optional[BaseException] = None
        self._handles: List[threading.Thread] = []
        self._control: "queue.Queue" = queue.Queue()
        worker_controls: List["queue.Queue"] = []

        def worker(t: int, control: "queue.Queue"):
            try:
                pending: Deque[Job] = deque()
                targetted: Deque[Job] = deque()
                wait_for_fingerprints = True
                while True:
                    if not pending:
                        pending = self._job_broker.pop()
                        if not pending:
                            return
                    if wait_for_fingerprints:
                        # Step 0: wait for someone to ask us to do work.
                        while True:
                            msg = control.get()
                            if msg is None:
                                return  # control channel closed
                            kind, fp = msg
                            if kind == _RUN_TO_COMPLETION:
                                wait_for_fingerprints = False
                                break
                            # _CHECK: look for the fp in our pending queue.
                            if not pending:
                                break
                            index = next(
                                (
                                    i
                                    for i, (_s, f, _e, _d) in enumerate(pending)
                                    if f == fp
                                ),
                                None,
                            )
                            if index is not None:
                                job = pending[index]
                                del pending[index]
                                targetted.append(job)
                                break
                    if not wait_for_fingerprints:
                        targetted.extend(pending)
                        pending.clear()

                    # Step 1: do work on the targetted slice.
                    self._check_block(targetted, pending, properties, visitor)
                    pending.extend(targetted)
                    targetted.clear()
                    if len(self._discoveries) == property_count:
                        return
                    if (
                        target_state_count is not None
                        and target_state_count <= self._state_count
                    ):
                        return
                    # Step 2: share work.
                    if len(pending) > 1 and thread_count > 1:
                        self._job_broker.split_and_push(pending)
            except BaseException as e:  # noqa: BLE001
                if self._worker_error is None:
                    self._worker_error = e
            finally:
                self._job_broker.close()
                self._finalize_coverage(set(self._discoveries))

        for t in range(thread_count):
            control: "queue.Queue" = queue.Queue()
            worker_controls.append(control)
            h = threading.Thread(
                target=worker, args=(t, control), name=f"checker-{t}", daemon=True
            )
            h.start()
            self._handles.append(h)

        def forwarder():
            while True:
                msg = self._control.get()
                for c in worker_controls:
                    c.put(msg)
                if msg is None:
                    return

        # The forwarder is deliberately NOT in handles: it lives as long as the
        # control channel and is a daemon thread, so join() after
        # run_to_completion() doesn't block on it.
        fh = threading.Thread(target=forwarder, name="control-forwarder", daemon=True)
        fh.start()
        self._forwarder = fh

    def _check_block(
        self,
        targetted: Deque[Job],
        pending: Deque[Job],
        properties,
        visitor,
    ) -> None:
        """Expand up to BLOCK_SIZE states from ``targetted``; newly generated
        states go back onto ``pending`` (to await the next control message)."""
        model = self._model
        generated = self._generated
        discoveries = self._discoveries
        local: List[Job] = []
        for _ in range(min(BLOCK_SIZE, len(targetted))):
            local.append(targetted.popleft())
        generated_count = 0
        block_size = len(local)
        block_max_depth = self._max_depth
        block_span = self._tracer.span("on_demand.block")
        block_span.__enter__()
        bc = BlockCoverage(self._cov, model)
        try:
            while local:
                state, state_fp, ebits, depth = local.pop()
                if depth > block_max_depth:
                    block_max_depth = depth
                bc.evaluated += 1
                if visitor is not None:
                    visitor.visit(
                        model, reconstruct_path(model, generated, state_fp)
                    )

                is_awaiting_discoveries = False
                for i, prop in enumerate(properties):
                    if prop.name in discoveries:
                        continue
                    if prop.expectation == Expectation.ALWAYS:
                        if not prop.condition(model, state):
                            discoveries[prop.name] = state_fp
                        else:
                            is_awaiting_discoveries = True
                        ant = prop.antecedent
                        if ant is None or ant(model, state):
                            bc.exercise(i)
                    elif prop.expectation == Expectation.SOMETIMES:
                        if prop.condition(model, state):
                            discoveries[prop.name] = state_fp
                            bc.exercise(i)
                        else:
                            is_awaiting_discoveries = True
                    else:  # EVENTUALLY
                        is_awaiting_discoveries = True
                        if prop.condition(model, state):
                            ebits = ebits - {i}
                        if i not in ebits:
                            bc.exercise(i)
                if not is_awaiting_discoveries:
                    return

                is_terminal = True
                succ = 0
                actions: List = []
                model.actions(state, actions)
                for action in actions:
                    next_state = model.next_state(state, action)
                    if next_state is None:
                        continue
                    if not model.within_boundary(next_state):
                        continue
                    generated_count += 1
                    succ += 1
                    next_fp = fingerprint(next_state)
                    if next_fp in generated:
                        is_terminal = False
                        bc.action(action, False)
                        continue
                    generated[next_fp] = state_fp
                    is_terminal = False
                    bc.action(action, True)
                    bc.depth[depth + 1] = bc.depth.get(depth + 1, 0) + 1
                    pending.appendleft((next_state, next_fp, ebits, depth + 1))
                bc.succ[succ] = bc.succ.get(succ, 0) + 1
                if is_terminal:
                    bc.terminals += 1
                    for i, prop in enumerate(properties):
                        # Insert-if-vacant: once a property has a discovery its
                        # ebit is no longer cleared during evaluation, so a
                        # stale set bit here must not overwrite the valid
                        # counterexample with a path that never tracked it
                        # (deviation: the reference overwrites, which can
                        # report an "eventually" trace ending in a state that
                        # satisfies the property; counts are unaffected).
                        if i in ebits and prop.name not in discoveries:
                            discoveries[prop.name] = state_fp
        finally:
            with self._count_lock:
                self._state_count += generated_count
                if block_max_depth > self._max_depth:
                    self._max_depth = block_max_depth
            self._bi.record(
                block_span,
                evaluated=block_size - len(local),
                generated=generated_count,
                max_depth=block_max_depth,
                unique_total=len(generated),
                pending=len(targetted) + len(pending),
            )
            bc.flush(max_depth=block_max_depth)

    # -- Checker surface ---------------------------------------------------

    def model(self):
        return self._model

    def check_fingerprint(self, fp: Fingerprint) -> None:
        self._control.put((_CHECK, fp))

    def run_to_completion(self) -> None:
        self._control.put((_RUN_TO_COMPLETION, None))

    def state_count(self) -> int:
        # Block-local counters flush once per check_block; clamp so the
        # documented invariant state_count >= unique_state_count holds for
        # mid-run polls too.
        return max(self._state_count, len(self._generated))

    def unique_state_count(self) -> int:
        return len(self._generated)

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: reconstruct_path(self._model, self._generated, fp)
            for name, fp in list(self._discoveries.items())
        }

    def handles(self) -> List[threading.Thread]:
        handles, self._handles = self._handles, []
        return handles

    def is_done(self) -> bool:
        return self._job_broker.is_closed() or len(self._discoveries) == len(
            self._model.properties()
        )

    def worker_error(self) -> Optional[BaseException]:
        return self._worker_error
