"""TPU random-walk checker: N vmapped simulation lanes in lockstep.

The host ``SimulationChecker`` rolls one trace per thread
(reference design: ``/root/reference/src/checker/simulation.rs``); here L
lanes advance together under one jitted ``lax.scan`` — per step each lane

1. restarts from a uniformly chosen initial state if its trace ended;
2. mirrors the host trace loop *in order*: depth-cap abort (no
   ``eventually`` discoveries), boundary exit (trace excludes the current
   state), on-device fingerprint + cycle check against the lane's own
   trace buffer (trace includes the current state), property evaluation,
   then a uniform choice among valid transitions (terminal exit when none);
3. on a first property hit anywhere in the batch, snapshots that lane's
   fingerprint trace into a per-property discovery buffer — the host
   replays it into a ``Path`` exactly like the other device checkers.

Like the reference, simulation only returns when every property has a
discovery or ``target_state_count`` is reached, and ``unique_state_count``
is approximated by the total count. Cycle-detection symmetry reduction is
host-only (use ``spawn_simulation`` for symmetric models); traces longer
than the lane buffer (``max_trace_len``) are aborted like a depth-cap.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.batch import BatchableModel
from ..core.model import Expectation
from ..core.path import Path
from ..ops.fingerprint import fingerprint_state, fp_to_int
from ..telemetry import device_step_annotation, get_tracer, metrics_registry
from .base import Checker

_NEG_INF = -1e30


def walk_lane_step(k, seeds, n_seeds, state, depth, ebits, done, thi, tlo,
                   key, depth_cap):
    """One walk step for a single lane — the shared trace-loop core of
    ``TpuSimulationChecker`` and the swarm kernel (``checker/swarm.py``),
    vmapped over lanes by each caller. Mirrors the host
    ``SimulationChecker`` loop *in order*: restart from the seed pool,
    depth cap, boundary exit, fingerprint + own-trace cycle check,
    property evaluation, uniform choice among valid transitions.

    ``k`` supplies the packed-model surface (``_model``/``_fp_fn``/
    ``_conditions``/``_ebit``/``_ebits0``/``_properties``/``_A``/``_D``
    and, when the swarm runs with coverage, ``_cov_layout``/
    ``_cov_antecedents``). ``depth_cap`` is a runtime scalar so one
    compiled shape serves every cap (the simulation checker pins it to
    its buffer depth ``D``). Returns the superset of per-step outputs;
    each caller's scan consumes its subset and XLA drops the rest —
    keeping ONE copy of the walk semantics is what guarantees the two
    walkers can never silently diverge."""
    model = k._model
    A, D = k._A, k._D
    key, k_init, k_act = jax.random.split(key, 3)

    # Restart ended lanes from a uniformly chosen seed state.
    init_idx = jax.random.randint(k_init, (), 0, n_seeds)
    restarted = done
    state = jax.tree_util.tree_map(
        lambda fresh, cur: jnp.where(done, fresh[init_idx], cur),
        seeds,
        state,
    )
    depth = jnp.where(done, 0, depth)
    ebits = jnp.where(done, k._ebits0, ebits)

    cap = jnp.minimum(jnp.int32(D), depth_cap)
    capped = depth >= cap
    # A cap hit BELOW the user's depth target (or with no target at
    # all) is a trace-buffer truncation, not a semantic bound — the
    # honest-overflow counter the reporter warns on.
    truncated = capped & (jnp.int32(D) < depth_cap)
    in_bounds = model.packed_within_boundary(state)
    boundary_end = ~capped & ~in_bounds

    hi, lo = k._fp_fn(state)
    slots = jnp.arange(D, dtype=jnp.int32)
    seen = slots < depth
    cycle = (seen & (thi == hi) & (tlo == lo)).any()
    # Record the current fingerprint (host appends before cycle break,
    # so cycle/terminal/property traces include the current state).
    write = ~capped & ~boundary_end
    thi = jnp.where(write & (slots == depth), hi, thi)
    tlo = jnp.where(write & (slots == depth), lo, tlo)
    cycle_end = write & cycle

    eval_ok = write & ~cycle
    cond_vals = [c(state) for c in k._conditions]
    ebits_after = ebits
    for pi, b in k._ebit.items():
        ebits_after = jnp.where(
            eval_ok & cond_vals[pi],
            ebits_after & ~jnp.uint32(1 << b),
            ebits_after,
        )

    # Uniform choice among valid transitions.
    aids = jnp.arange(A, dtype=jnp.int32)
    cand, cvalid = jax.vmap(lambda a: model.packed_step(state, a))(aids)
    cvalid = cvalid & eval_ok
    terminal = eval_ok & ~cvalid.any()
    logits = jnp.where(cvalid, 0.0, _NEG_INF)
    choice = jax.random.categorical(k_act, logits)
    advanced = eval_ok & ~terminal
    state = jax.tree_util.tree_map(
        lambda c, cur: jnp.where(advanced, c[choice], cur), cand, state
    )

    ebits_end = boundary_end | cycle_end | terminal
    done = capped | ebits_end
    # Trace length as the host's fingerprint_path would have it (capped
    # and out-of-boundary exits happen before the host appends).
    path_len = jnp.where(capped | boundary_end, depth, depth + 1)
    depth = jnp.where(advanced, depth + 1, depth)

    cov_layout = getattr(k, "_cov_layout", None)
    per_prop = []
    exercised = []
    for i, p in enumerate(k._properties):
        if p.expectation == Expectation.ALWAYS:
            hit = eval_ok & ~cond_vals[i]
        elif p.expectation == Expectation.SOMETIMES:
            hit = eval_ok & cond_vals[i]
        else:
            b = k._ebit[i]
            hit = ebits_end & (((ebits_after >> jnp.uint32(b)) & 1) == 1)
        per_prop.append(hit)
        if cov_layout is not None:
            if p.expectation == Expectation.ALWAYS:
                ant = k._cov_antecedents[i]
                exercised.append(
                    eval_ok & ant(state) if ant is not None else eval_ok
                )
            elif p.expectation == Expectation.SOMETIMES:
                exercised.append(eval_ok & cond_vals[i])
            else:
                eb = k._ebit[i]
                exercised.append(
                    eval_ok
                    & (((ebits_after >> jnp.uint32(eb)) & 1) == 0)
                )
    hits = (
        jnp.stack(per_prop) if per_prop else jnp.zeros((0,), bool)
    )

    out = {
        "state": state,
        "depth": depth,
        "ebits": ebits_after,
        "done": done,
        "thi": thi,
        "tlo": tlo,
        "key": key,
        "counted": eval_ok,
        "hits": hits,
        "path_len": path_len,
        "capped": capped,
        "hi": hi,
        "lo": lo,
        "write": write,
        "restarted": restarted,
        "truncated": truncated,
    }
    if cov_layout is not None:
        out["cvalid"] = cvalid
        out["choice"] = choice
        out["advanced"] = advanced
        out["exercised"] = (
            jnp.stack(exercised)
            if exercised
            else jnp.zeros((0,), bool)
        )
    return out


def walk_kernel_surface(model):
    """The packed walk-kernel contract both walkers build at init:
    aligned condition callables, the eventually-property bit map, and
    the all-pending ebits seed. One copy so the eventually-bit encoding
    ``walk_lane_step`` consumes can never diverge between them. Returns
    ``(properties, conditions, ebit, ebits0)``."""
    properties = model.properties()
    conditions = model.packed_conditions()
    if len(conditions) != len(properties):
        raise ValueError(
            "packed_conditions() must align 1:1 with properties(): "
            f"{len(conditions)} != {len(properties)}"
        )
    eventually = [
        i
        for i, p in enumerate(properties)
        if p.expectation == Expectation.EVENTUALLY
    ]
    if len(eventually) > 32:
        raise ValueError("at most 32 eventually properties supported")
    ebit: Dict[int, int] = {pi: b for b, pi in enumerate(eventually)}
    ebits0 = np.uint32(sum(1 << b for b in ebit.values()))
    return properties, conditions, ebit, ebits0


def capture_discoveries(disc, out, P):
    """First-hit discovery capture shared by both walkers: for each of
    the P properties with a hit anywhere in the batch this step,
    snapshot the hitting lane's fingerprint trace into the per-property
    discovery buffers exactly once — the first step that hits wins, and
    later hits leave the recorded trace untouched. One copy for the
    same reason as ``walk_lane_step``: a tie-break or trace-length
    change must not silently diverge the walkers' discovery traces."""
    hits = out["hits"]  # (L, P) after the callers' lane vmap
    for i in range(P):
        lane = jnp.argmax(hits[:, i])
        any_hit = hits[:, i].any()
        found_now = any_hit & ~disc["found"][i]
        disc = {
            "found": disc["found"].at[i].set(disc["found"][i] | any_hit),
            "hi": disc["hi"].at[i].set(
                jnp.where(found_now, out["thi"][lane], disc["hi"][i])
            ),
            "lo": disc["lo"].at[i].set(
                jnp.where(found_now, out["tlo"][lane], disc["lo"][i])
            ),
            "len": disc["len"].at[i].set(
                jnp.where(found_now, out["path_len"][lane], disc["len"][i])
            ),
        }
    return disc


class TpuSimulationChecker(Checker):
    # Honest capability surface (the PR 12 convention): the host-paced
    # step loop has no resumable payload and no shared-dispatch packing
    # — ``spawn_swarm`` is the device-resident walker that has both.
    supports_preempt = False
    supports_packing = False
    packing_reason = (
        "host-paced step loop (spawn_swarm is the packable walker)"
    )

    def __init__(
        self,
        options,
        seed: int,
        lanes: int = 1024,
        steps_per_call: int = 64,
        max_trace_len: Optional[int] = None,
    ):
        model = options.model
        if not isinstance(model, BatchableModel):
            raise TypeError(
                f"spawn_tpu_simulation requires a BatchableModel; "
                f"{type(model).__name__} does not implement the packed protocol"
            )
        if options._symmetry is not None:
            raise NotImplementedError(
                "symmetry-aware cycle detection is host-only; use "
                "spawn_simulation for symmetric models"
            )
        self._model = model
        (
            self._properties,
            self._conditions,
            self._ebit,
            self._ebits0,
        ) = walk_kernel_surface(model)
        self._A = model.packed_action_count()
        self._L = lanes
        self._K = steps_per_call
        self._depth_cap = options._target_max_depth
        self._D = max_trace_len or (self._depth_cap or 512)
        if self._depth_cap is not None:
            self._D = min(self._D, self._depth_cap)
        self._target_state_count = options._target_state_count
        if options._visitor is not None:
            raise NotImplementedError(
                "per-state visitors replay O(depth²) host paths; use "
                "spawn_simulation for visitor-driven runs"
            )
        self._seed = seed

        self._state_count = 0
        self._max_depth = 0
        # Trace-buffer truncation honesty: a lane hitting the buffer
        # limit D BELOW the user's depth cap (or with no cap at all) was
        # silently aborted — counted per step call and warned about at
        # run end, so truncation is never mistaken for absence.
        self._trace_overflows = 0
        self._buffer_truncates = (
            self._depth_cap is None or self._D < self._depth_cap
        )
        self._discoveries_fps: Dict[str, List[int]] = {}
        self._empty_discoveries: set = set()
        self._done_event = threading.Event()
        self._error: Optional[BaseException] = None

        self._fp_fn = model.packed_fingerprint
        self._jit_steps = jax.jit(self._run_steps)
        self._jit_fp_single = jax.jit(self._fp_fn)

        self._handles = [
            threading.Thread(target=self._run, name="tpu-sim", daemon=True)
        ]
        self._handles[0].start()

    # -- device kernel -----------------------------------------------------

    def _lane_step(self, inits, n_init, state, depth, ebits, done, thi, tlo, key):
        """One host-loop iteration for a single lane (vmapped); the body
        is the ``walk_lane_step`` core shared with the swarm kernel. The
        cap is pinned to the buffer depth D — the host-side
        ``_buffer_truncates`` flag decides whether hitting it was a
        semantic bound or a truncation."""
        return walk_lane_step(
            self, inits, n_init, state, depth, ebits, done, thi, tlo,
            key, jnp.int32(self._D),
        )

    def _run_steps(self, carry):
        inits = self._model.packed_init_states()
        n_init = jax.tree_util.tree_leaves(inits)[0].shape[0]
        P = len(self._properties)

        def body(c, _):
            lanes, stats, disc = c
            out = jax.vmap(
                lambda s, d, e, dn, th, tl, k: self._lane_step(
                    inits, n_init, s, d, e, dn, th, tl, k
                )
            )(
                lanes["state"],
                lanes["depth"],
                lanes["ebits"],
                lanes["done"],
                lanes["thi"],
                lanes["tlo"],
                lanes["key"],
            )
            lanes = {
                k: out[k]
                for k in ("state", "depth", "ebits", "done", "thi", "tlo", "key")
            }
            stats = {
                "count": stats["count"] + out["counted"].sum(dtype=jnp.int32),
                "max_depth": jnp.maximum(
                    stats["max_depth"], out["path_len"].max()
                ),
                "overflow": stats["overflow"]
                + out["capped"].sum(dtype=jnp.int32),
            }
            if P:
                disc = capture_discoveries(disc, out, P)
            return (lanes, stats, disc), None

        carry, _ = jax.lax.scan(body, carry, None, length=self._K)
        return carry

    # -- host loop ---------------------------------------------------------

    def _run(self):
        try:
            self._explore()
        except BaseException as e:  # noqa: BLE001 - surfaced via worker_error
            self._error = e
        finally:
            self._done_event.set()

    def _fresh_carry(self):
        L, D, P = self._L, self._D, len(self._properties)
        inits = self._model.packed_init_states()
        lanes = {
            "state": jax.tree_util.tree_map(
                lambda x: jnp.zeros((L,) + x.shape[1:], x.dtype), inits
            ),
            "depth": jnp.zeros((L,), jnp.int32),
            "ebits": jnp.zeros((L,), jnp.uint32),
            "done": jnp.ones((L,), bool),  # all lanes restart on step one
            "thi": jnp.zeros((L, D), jnp.uint32),
            "tlo": jnp.zeros((L, D), jnp.uint32),
            "key": jax.vmap(
                lambda i: jax.random.fold_in(jax.random.PRNGKey(self._seed), i)
            )(jnp.arange(L)),
        }
        stats = {
            "count": jnp.int32(0),
            "max_depth": jnp.int32(0),
            "overflow": jnp.int32(0),
        }
        disc = {
            "found": jnp.zeros((P,), bool),
            "hi": jnp.zeros((P, D), jnp.uint32),
            "lo": jnp.zeros((P, D), jnp.uint32),
            "len": jnp.zeros((P,), jnp.int32),
        }
        return (lanes, stats, disc)

    def _explore(self):
        props = self._properties
        if not props:
            return
        carry = self._fresh_carry()
        tracer = get_tracer()
        reg = metrics_registry()
        m_calls = reg.counter("tpu_sim.step_calls")
        m_states = reg.counter("tpu_sim.states_visited")
        # Shared family with checker/swarm.py — the truncation signal
        # reads the same whichever walker produced it.
        m_overflow = reg.counter("swarm.trace_overflow")
        # The device counter is int32 (jnp.int64 needs x64 mode) and would
        # wrap after ~2.15B counted lane-steps if carried across calls, so
        # each _jit_steps call counts from zero and the host accumulates.
        count = 0
        calls = 0
        while True:
            calls += 1
            with tracer.span(
                "tpu_sim.steps", call=calls, lanes=self._L,
                steps_per_call=self._K,
            ) as sp, device_step_annotation("tpu_sim.steps", calls):
                carry = self._jit_steps(carry)
                lanes, stats, disc = carry
                step_count = int(stats["count"])
                sp.set(states=step_count)
            m_calls.inc()
            m_states.inc(step_count)
            count += step_count
            self._state_count = count
            self._max_depth = max(self._max_depth, int(stats["max_depth"]))
            if self._buffer_truncates:
                overflow = int(stats["overflow"])
                if overflow:
                    m_overflow.inc(overflow)
                    self._trace_overflows += overflow
            carry = (
                lanes,
                {
                    "count": jnp.int32(0),
                    "max_depth": stats["max_depth"],
                    "overflow": jnp.int32(0),
                },
                disc,
            )
            found = np.asarray(disc["found"])
            if found.any():
                hi = np.asarray(disc["hi"]).astype(np.uint64)
                lo = np.asarray(disc["lo"]).astype(np.uint64)
                lens = np.asarray(disc["len"])
                for i, p in enumerate(props):
                    if found[i] and p.name not in self._discoveries_fps:
                        n = int(lens[i])
                        if n == 0:
                            # A lane whose trace ended before visiting any
                            # state (out-of-boundary init) has no path to
                            # report; count it as settled so the run can end,
                            # but surface no (empty) Path.
                            self._empty_discoveries.add(p.name)
                            continue
                        self._empty_discoveries.discard(p.name)
                        fps = ((hi[i, :n] << np.uint64(32)) | lo[i, :n]).tolist()
                        self._discoveries_fps[p.name] = fps
            settled = set(self._discoveries_fps) | self._empty_discoveries
            if len(settled) == len(props):
                return
            if (
                self._target_state_count is not None
                and self._target_state_count <= count
            ):
                return
            # Like the host checker, keep sampling until discoveries or the
            # target are reached — no other exit (reference-parity).

    # -- path reconstruction ----------------------------------------------

    def _host_fp(self, host_state) -> int:
        hi, lo = self._jit_fp_single(self._model.pack_state(host_state))
        return fp_to_int(hi, lo)

    # -- Checker surface ---------------------------------------------------

    def model(self):
        return self._model

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        # Like the reference, approximated by the total count.
        return self._state_count

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: Path.from_fingerprints(self._model, fps, fp_of=self._host_fp)
            for name, fps in list(self._discoveries_fps.items())
        }

    def handles(self) -> List[threading.Thread]:
        handles, self._handles = self._handles, []
        return handles

    def is_done(self) -> bool:
        return self._done_event.is_set()

    def worker_error(self) -> Optional[BaseException]:
        return self._error
