"""Random-walk (simulation) host checker.

Repeatedly rolls a trace from a random init state via a pluggable ``Chooser``
until loop/boundary/terminal, evaluating properties along the trace. For state
spaces too large to exhaust. Note: like the reference, simulation only
terminates when every property has a discovery or ``target_state_count`` is
reached — otherwise it keeps sampling traces.

Reference design: ``SimulationChecker`` at
``/root/reference/src/checker/simulation.rs``. The TPU counterpart runs N
vmapped lanes in parallel (``stateright_tpu.checker.tpu_simulation``).
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

from ..core.fingerprint import Fingerprint, fingerprint
from ..core.model import Expectation
from ..core.path import Path
from ..telemetry import get_tracer, metrics_registry
from .base import Checker


class Chooser:
    """Chooses transitions during a simulation run. Created per thread."""

    def new_state(self, seed: int):
        raise NotImplementedError

    def choose_initial_state(self, chooser_state, initial_states: List) -> int:
        raise NotImplementedError

    def choose_action(self, chooser_state, current_state, actions: List) -> int:
        raise NotImplementedError


class UniformChooser(Chooser):
    """Makes uniform random choices."""

    def new_state(self, seed: int):
        return random.Random(seed)

    def choose_initial_state(self, rng, initial_states):
        return rng.randrange(len(initial_states))

    def choose_action(self, rng, current_state, actions):
        return rng.randrange(len(actions))


class SimulationChecker(Checker):
    # Honest capability surface (the PR 12 convention): host threads
    # have no resumable payload format and nothing to co-dispatch.
    supports_preempt = False
    supports_packing = False
    packing_reason = (
        "host-threaded walker (no shared device dispatch to pack into)"
    )

    def __init__(self, options, seed: int, chooser: Chooser):
        model = options.model
        self._model = model
        symmetry = options._symmetry
        target_state_count = options._target_state_count
        target_max_depth = options._target_max_depth
        visitor = options._visitor
        properties = model.properties()
        property_count = len(properties)

        self._state_count = 0
        self._count_lock = threading.Lock()
        self._max_depth = 0
        self._discoveries: Dict[str, List[Fingerprint]] = {}
        # One span per rolled trace (not per step): simulation traces are
        # the unit the reference reasons about, and tiny traces stay off
        # the per-state hot loop.
        self._tracer = get_tracer()
        reg = metrics_registry()
        self._m_traces = reg.counter("simulation.traces")
        self._m_steps = reg.counter("simulation.states_visited")
        self._m_trace_len = reg.histogram("simulation.trace_len")
        self._worker_error: Optional[BaseException] = None
        self._handles: List[threading.Thread] = []
        self._stop = threading.Event()

        def worker(thread_seed: int):
            try:
                rng = random.Random(thread_seed)
                trace_seed = thread_seed
                while not self._stop.is_set():
                    with self._tracer.span(
                        "simulation.trace", seed=trace_seed
                    ) as sp:
                        trace_len = self._check_trace_from_initial(
                            trace_seed,
                            chooser,
                            properties,
                            visitor,
                            target_max_depth,
                            symmetry,
                        )
                        sp.set(trace_len=trace_len)
                    self._m_traces.inc()
                    self._m_steps.inc(trace_len)
                    self._m_trace_len.observe(trace_len)
                    if len(self._discoveries) == property_count:
                        return
                    if (
                        target_state_count is not None
                        and target_state_count <= self._state_count
                    ):
                        return
                    trace_seed = rng.getrandbits(64)
            except BaseException as e:  # noqa: BLE001
                if self._worker_error is None:
                    self._worker_error = e
                self._stop.set()

        for t in range(max(1, options._thread_count)):
            h = threading.Thread(
                target=worker, args=(seed + t,), name=f"checker-{t}", daemon=True
            )
            h.start()
            self._handles.append(h)

    def _check_trace_from_initial(
        self, seed, chooser, properties, visitor, target_max_depth, symmetry
    ):
        model = self._model
        discoveries = self._discoveries
        chooser_state = chooser.new_state(seed)

        initial_states = model.init_states()
        index = chooser.choose_initial_state(chooser_state, initial_states)
        state = initial_states[index]

        fingerprint_path: List[Fingerprint] = []
        generated = set()  # fingerprints seen in this run, for cycle detection
        ebits = frozenset(
            i
            for i, p in enumerate(properties)
            if p.expectation == Expectation.EVENTUALLY
        )
        while True:
            if len(fingerprint_path) > self._max_depth:
                with self._count_lock:
                    if len(fingerprint_path) > self._max_depth:
                        self._max_depth = len(fingerprint_path)
            if (
                target_max_depth is not None
                and len(fingerprint_path) >= target_max_depth
            ):
                # Return (not break): we don't know whether this is terminal,
                # so unmet eventually bits must not become discoveries.
                return len(fingerprint_path)
            if not model.within_boundary(state):
                break

            fingerprint_path.append(fingerprint(state))
            key = (
                fingerprint(symmetry(state)) if symmetry else fingerprint_path[-1]
            )
            if key in generated:
                break  # found a loop
            generated.add(key)

            with self._count_lock:
                self._state_count += 1

            if visitor is not None:
                visitor.visit(
                    model, Path.from_fingerprints(model, fingerprint_path)
                )

            is_awaiting_discoveries = False
            for i, prop in enumerate(properties):
                if prop.name in discoveries:
                    continue
                if prop.expectation == Expectation.ALWAYS:
                    if not prop.condition(model, state):
                        discoveries[prop.name] = list(fingerprint_path)
                    else:
                        is_awaiting_discoveries = True
                elif prop.expectation == Expectation.SOMETIMES:
                    if prop.condition(model, state):
                        discoveries[prop.name] = list(fingerprint_path)
                    else:
                        is_awaiting_discoveries = True
                else:  # EVENTUALLY
                    is_awaiting_discoveries = True
                    if prop.condition(model, state):
                        ebits = ebits - {i}
            if not is_awaiting_discoveries:
                break

            actions: List = []
            model.actions(state, actions)
            # Choose actions until one yields a next state or none remain.
            advanced = False
            while actions:
                index = chooser.choose_action(chooser_state, state, actions)
                action = actions[index]
                actions[index] = actions[-1]
                actions.pop()
                next_state = model.next_state(state, action)
                if next_state is not None:
                    state = next_state
                    advanced = True
                    break
            if not advanced:
                break  # terminal: still check eventually properties below

        for i, prop in enumerate(properties):
            # Insert-if-vacant — see the matching note in bfs.py. A trace that
            # ended before visiting any state (out-of-boundary init) has no
            # path to report and is skipped.
            if i in ebits and fingerprint_path and prop.name not in discoveries:
                discoveries[prop.name] = list(fingerprint_path)
        return len(fingerprint_path)

    # -- Checker surface ---------------------------------------------------

    def model(self):
        return self._model

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        # Unique states are not tracked across runs; approximated by total.
        return self._state_count

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: Path.from_fingerprints(self._model, fps)
            for name, fps in list(self._discoveries.items())
        }

    def handles(self) -> List[threading.Thread]:
        handles, self._handles = self._handles, []
        return handles

    def is_done(self) -> bool:
        return all(not h.is_alive() for h in self._handles) or bool(
            self._stop.is_set()
        )

    def worker_error(self) -> Optional[BaseException]:
        return self._worker_error
