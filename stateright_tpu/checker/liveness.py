"""Complete (cycle-aware) ``eventually`` checking — opt-in, beyond the
reference.

The reference's BFS/DFS only flag an ``eventually`` counterexample at a
TERMINAL state with the condition still unmet; paths that diverge into a
cycle (or rejoin previously-visited states) are documented false negatives
(FIXMEs at ``/root/reference/src/checker/bfs.rs:285-305``, test
``src/checker.rs:642-659``). The default checkers here reproduce those
semantics bit-for-bit (``tests/test_checker.py``) — counts and verdicts
must not silently diverge from the reference. The known-wrong
terminal-state merge at DAG joins is PINNED by regression tests on both
paths: ``tests/test_liveness.py::
test_terminal_counterexample_masked_by_dag_join_found`` (host BFS) and
``tests/test_liveness.py::
test_terminal_merge_at_dag_join_pinned_on_device_checker`` (device wave
dedup) assert the default semantics still miss it and this post-pass
still finds it.

``CheckerBuilder.complete_liveness()`` adds the missing half as a
post-pass: for every ``eventually`` property still without a discovery,
search the condition-false region for a maximal path that never satisfies
the condition. In a finite space such a path is either a **lasso** — a
condition-false path from an initial state that closes a cycle — or a
condition-false path ending at a **terminal** state (no within-boundary
successors at all). The second shape matters even though the default
checkers nominally handle terminal states: their eventually-bits are
merged at DAG joins (the first reference FIXME), so a terminal
counterexample reached second via a join is masked; the post-pass
re-derives it from scratch. Any path that touches a satisfying state is
no counterexample, so the search runs entirely inside the
condition-false region: a host DFS from condition-false initial states,
following only condition-false successors, returning on a back edge to a
state on the current DFS path (gray) — the lasso certificate, a concrete
path whose final state revisits an earlier one — or on reaching a state
with no successors in the full model — the maximal-path certificate.
Together the two shapes are exhaustive, so the pass is exact: it finds a
counterexample iff one exists within the boundary.

The pass is self-contained (it re-expands on the host model; it does not
need the checker's visited set), exact for finite boundaries, and costs
O(size of the reachable condition-false region) in host time and memory —
which is why it is opt-in rather than always-on.

**Practical scale ceiling.** The O(region) bound is the *certify-absence*
cost: when no counterexample exists the DFS must exhaust the region, at
one host ``actions``+``next_state`` expansion per false state (≈ the host
``BfsChecker``'s per-state cost, thousands-to-tens-of-thousands of
states/s depending on the model — ``tests/test_liveness.py`` pins a
100K-state absence certification in the fast lane). When a counterexample
EXISTS, depth-first order typically finds a certificate after a tiny
fraction of the region: raft-3 (lossy, the ``check-live`` CLI config)
yields its stable-leader lasso in well under a second. Budget for the
region-exhaust case when opting in at raft-5 scale (a ~735K-state false
region ≈ minutes of single-threaded host time); the device checkers'
parent-pointer store cannot shortcut this — it records tree edges only,
and cycle detection needs the full edge relation.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..core.model import Expectation, Property
from ..core.path import Path

__all__ = [
    "INCONCLUSIVE",
    "find_eventually_lasso",
    "lasso_discoveries",
    "lasso_discoveries_ex",
    "checker_lasso_pass",
]


class _Inconclusive:
    """Sentinel: the pass ran out of its state budget or deadline before
    it could certify either way. Distinct from None (= absence
    certified) because conflating them would turn an aborted search
    into a silent 'property holds'."""

    def __repr__(self):  # pragma: no cover - debugging nicety
        return "INCONCLUSIVE"


INCONCLUSIVE = _Inconclusive()


def find_eventually_lasso(model, prop: Property, budget_states=None,
                          deadline_s=None) -> Optional[Path]:
    """A counterexample for one ``eventually`` property, or None.

    Iterative DFS over the condition-false region with white/gray/black
    coloring. Two certificate shapes, exhaustive for finite boundaries:
    a successor that is gray closes a cycle (lasso), and a visited state
    with no within-boundary successors in the FULL model ends a maximal
    path (the terminal case the default checkers can mask via their
    eventually-bit merge at DAG joins — ``bfs.py``'s parity NOTE). A
    state whose successors all satisfy the condition is neither: every
    maximal path through it satisfies the property. States must be
    hashable (the host checkers' standing requirement).

    ``budget_states`` / ``deadline_s`` bound the search: when either is
    exhausted before a certificate or a full region exhaust, the pass
    returns :data:`INCONCLUSIVE` — an HONEST third outcome, never
    conflated with "no counterexample" (None). This is what keeps an
    opted-in raft-5-scale run from stalling ``discoveries()`` for
    unbounded host minutes.
    """
    cond = prop.condition
    deadline_t = (
        time.monotonic() + deadline_s if deadline_s is not None else None
    )
    expanded = 0
    # Deadline polls are batched (every 256 expansions) so the budget
    # machinery costs nothing against the per-state model expansion.
    _POLL = 256

    def over_budget() -> bool:
        nonlocal expanded
        expanded += 1
        if budget_states is not None and expanded > budget_states:
            return True
        return (
            deadline_t is not None
            and expanded % _POLL == 0
            and time.monotonic() > deadline_t
        )

    def expand(state):
        """(had_any_successor, condition-false successors). The first
        component uses the full successor set — terminality must match
        the host BFS's notion (``bfs.py``: any action yielding a
        non-None, within-boundary next state), not the false region's."""
        acts: List = []
        model.actions(state, acts)
        any_within = False
        false_succs: List = []
        for a in acts:
            ns = model.next_state(state, a)
            if ns is None or not model.within_boundary(ns):
                continue
            any_within = True
            if not cond(model, ns):
                false_succs.append((a, ns))
        return any_within, false_succs

    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict = {}
    for init in model.init_states():
        if not model.within_boundary(init) or cond(model, init):
            continue
        if color.get(init, WHITE) != WHITE:
            continue
        color[init] = GRAY
        if over_budget():
            return INCONCLUSIVE
        any_within, succs = expand(init)
        if not any_within:
            # Terminal condition-false init: a one-state maximal path.
            return Path([(init, None)])
        stack = [(init, iter(succs))]
        trail: List = [init]  # states on the current DFS path
        actions: List = []  # actions between them (len == len(trail) - 1)
        while stack:
            state, it = stack[-1]
            descended = False
            for action, nxt in it:
                c = color.get(nxt, WHITE)
                if c == GRAY:
                    # Cycle: trail + the closing edge revisits `nxt`.
                    steps = [
                        (s, a) for s, a in zip(trail, actions + [action])
                    ]
                    steps.append((nxt, None))
                    return Path(steps)
                if c == WHITE:
                    color[nxt] = GRAY
                    if over_budget():
                        return INCONCLUSIVE
                    any_within, nsuccs = expand(nxt)
                    if not any_within:
                        # Terminal condition-false state: trail + the
                        # closing edge is a maximal never-satisfying path.
                        steps = [
                            (s, a) for s, a in zip(trail, actions + [action])
                        ]
                        steps.append((nxt, None))
                        return Path(steps)
                    stack.append((nxt, iter(nsuccs)))
                    trail.append(nxt)
                    actions.append(action)
                    descended = True
                    break
            if not descended:
                color[state] = BLACK
                stack.pop()
                trail.pop()
                if actions:
                    actions.pop()
    return None


def checker_lasso_pass(checker, done: bool, have) -> Dict[str, Path]:
    """The lazy post-pass every checker's ``discoveries()`` shares.

    Runs once per checker (cached under ``checker._lasso_lock``) when the
    opt-in flag is set AND exploration finished cleanly — a crashed run
    must not launch an unbounded host DFS from ``discoveries()`` (callers
    often inspect a failed checker), nor report counterexamples for a run
    that never completed. A crashed run's skip is SIGNALED
    (``liveness.skipped_crashed_run`` counter + reporter warning via
    ``Checker._signal_liveness_skip``), never silent — ``{}`` from a
    crashed run must not read as "no counterexample exists". ``have`` is
    the checker's existing discovery-name collection (terminal-state
    counterexamples win). Budget knobs
    (``.complete_liveness(budget_states=, deadline_s=)``) bound the pass;
    properties it could not certify land in
    ``checker._lasso_inconclusive`` and the ``liveness.inconclusive``
    metric instead of stalling the caller for unbounded host minutes."""
    if not checker._complete_liveness or not done:
        return {}
    if checker.worker_error() is not None:
        checker._signal_liveness_skip()
        return {}
    with checker._lasso_lock:
        if checker._lassos is None:
            props = getattr(checker, "_properties", None)
            if props is None:
                props = checker._model.properties()
            paths, inconclusive = lasso_discoveries_ex(
                checker._model,
                props,
                set(have),
                budget_states=getattr(
                    checker, "_lasso_budget_states", None
                ),
                deadline_s=getattr(checker, "_lasso_deadline_s", None),
            )
            checker._lasso_inconclusive = inconclusive
            if inconclusive:
                try:
                    reg = checker.metrics()
                    reg.counter("liveness.inconclusive").inc(
                        len(inconclusive)
                    )
                except Exception:  # noqa: BLE001 - signal only
                    pass
            checker._lassos = paths
    return checker._lassos


def lasso_discoveries(model, properties, have, budget_states=None,
                      deadline_s=None) -> Dict[str, Path]:
    """Counterexamples (lasso or masked-terminal maximal path) for every
    undiscovered ``eventually`` property. ``have`` is the checker's
    existing discovery-name set (first-found wins; counterexamples the
    default semantics already reported stay as-is)."""
    return lasso_discoveries_ex(
        model, properties, have, budget_states=budget_states,
        deadline_s=deadline_s,
    )[0]


def lasso_discoveries_ex(model, properties, have, budget_states=None,
                         deadline_s=None,
                         ) -> Tuple[Dict[str, Path], List[str]]:
    """``lasso_discoveries`` plus the honest third outcome: the names
    the bounded pass could NOT certify (budget or deadline exhausted).
    The deadline is shared across properties — one runaway region must
    not starve the rest AND still overrun the caller's bound."""
    deadline_t = (
        time.monotonic() + deadline_s if deadline_s is not None else None
    )
    out: Dict[str, Path] = {}
    inconclusive: List[str] = []
    for prop in properties:
        if prop.expectation != Expectation.EVENTUALLY:
            continue
        if prop.name in have:
            continue
        remaining = (
            max(0.001, deadline_t - time.monotonic())
            if deadline_t is not None
            else None
        )
        path = find_eventually_lasso(
            model, prop, budget_states=budget_states,
            deadline_s=remaining,
        )
        if path is INCONCLUSIVE:
            inconclusive.append(prop.name)
        elif path is not None:
            out[prop.name] = path
    return out, inconclusive
