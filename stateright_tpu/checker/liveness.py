"""Complete (cycle-aware) ``eventually`` checking — opt-in, beyond the
reference.

The reference's BFS/DFS only flag an ``eventually`` counterexample at a
TERMINAL state with the condition still unmet; paths that diverge into a
cycle (or rejoin previously-visited states) are documented false negatives
(FIXMEs at ``/root/reference/src/checker/bfs.rs:285-305``, test
``src/checker.rs:642-659``). The default checkers here reproduce those
semantics bit-for-bit (``tests/test_checker.py``) — counts and verdicts
must not silently diverge from the reference.

``CheckerBuilder.complete_liveness()`` adds the missing half as a
post-pass: for every ``eventually`` property still without a discovery,
search for a **lasso** — a path from an initial state that never satisfies
the condition and closes a cycle. Any infinite counterexample path in a
finite space is exactly such a lasso, and any path that touches a
satisfying state is no counterexample, so the search runs entirely inside
the condition-false region: a host DFS from condition-false initial
states, following only condition-false successors, looking for a back
edge to a state on the current DFS path (gray). The resulting discovery
is a finite certificate: a concrete path whose final state revisits an
earlier state with the condition false at every step.

The pass is self-contained (it re-expands on the host model; it does not
need the checker's visited set), exact for finite boundaries, and costs
O(size of the reachable condition-false region) in host time and memory —
which is why it is opt-in rather than always-on.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..core.model import Expectation, Property
from ..core.path import Path

__all__ = [
    "find_eventually_lasso",
    "lasso_discoveries",
    "checker_lasso_pass",
]


def find_eventually_lasso(model, prop: Property) -> Optional[Path]:
    """A lasso counterexample for one ``eventually`` property, or None.

    Iterative DFS over the condition-false region with white/gray/black
    coloring; a successor that is gray closes the cycle. States must be
    hashable (the host checkers' standing requirement).
    """
    cond = prop.condition

    def false_succs(state):
        acts: List = []
        model.actions(state, acts)
        for a in acts:
            ns = model.next_state(state, a)
            if (
                ns is not None
                and model.within_boundary(ns)
                and not cond(model, ns)
            ):
                yield a, ns

    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict = {}
    for init in model.init_states():
        if not model.within_boundary(init) or cond(model, init):
            continue
        if color.get(init, WHITE) != WHITE:
            continue
        color[init] = GRAY
        stack = [(init, false_succs(init))]
        trail: List = [init]  # states on the current DFS path
        actions: List = []  # actions between them (len == len(trail) - 1)
        while stack:
            state, it = stack[-1]
            descended = False
            for action, nxt in it:
                c = color.get(nxt, WHITE)
                if c == GRAY:
                    # Cycle: trail + the closing edge revisits `nxt`.
                    steps = [
                        (s, a) for s, a in zip(trail, actions + [action])
                    ]
                    steps.append((nxt, None))
                    return Path(steps)
                if c == WHITE:
                    color[nxt] = GRAY
                    stack.append((nxt, false_succs(nxt)))
                    trail.append(nxt)
                    actions.append(action)
                    descended = True
                    break
            if not descended:
                color[state] = BLACK
                stack.pop()
                trail.pop()
                if actions:
                    actions.pop()
    return None


def checker_lasso_pass(checker, done: bool, have) -> Dict[str, Path]:
    """The lazy post-pass every checker's ``discoveries()`` shares.

    Runs once per checker (cached under ``checker._lasso_lock``) when the
    opt-in flag is set AND exploration finished cleanly — a crashed run
    must not launch an unbounded host DFS from ``discoveries()`` (callers
    often inspect a failed checker), nor report counterexamples for a run
    that never completed. ``have`` is the checker's existing
    discovery-name collection (terminal-state counterexamples win)."""
    if not checker._complete_liveness or not done:
        return {}
    if checker.worker_error() is not None:
        return {}
    with checker._lasso_lock:
        if checker._lassos is None:
            props = getattr(checker, "_properties", None)
            if props is None:
                props = checker._model.properties()
            checker._lassos = lasso_discoveries(
                checker._model, props, set(have)
            )
    return checker._lassos


def lasso_discoveries(model, properties, have) -> Dict[str, Path]:
    """Lasso counterexamples for every undiscovered ``eventually``
    property. ``have`` is the checker's existing discovery-name set
    (first-found wins; terminal-state counterexamples stay as-is)."""
    out: Dict[str, Path] = {}
    for prop in properties:
        if prop.expectation != Expectation.EVENTUALLY:
            continue
        if prop.name in have:
            continue
        path = find_eventually_lasso(model, prop)
        if path is not None:
            out[prop.name] = path
    return out
