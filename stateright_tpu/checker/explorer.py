"""Explorer: interactive web UI over the on-demand checker.

``CheckerBuilder.serve(address)`` starts an HTTP service backed by
``OnDemandChecker`` — states are computed lazily as the user browses, and
browsing a state nudges the checker to explore it (so properties get
verified along the user's path of interest).

HTTP surface (reference: ``/root/reference/src/checker/explorer.rs``):

- ``GET /.status`` → ``StatusView`` JSON: progress counters, per-property
  discovery paths, a recently sampled path, and the live-monitor
  ``progress`` estimate (EWMA states/s, ETA band — the same fields the
  monitor server's ``/status`` reports);
- ``GET /.states/fp1/fp2/...`` → ``StateView`` JSON: replays the
  fingerprint path through the model, evaluates properties at the final
  state, renders the model's SVG hook, and enumerates next steps;
- ``POST /.runtocompletion`` → unblocks the checker to exhaust the space;
- ``GET /metrics`` / ``/status`` / ``/events`` → the live-monitor
  endpoints (Prometheus text, JSON snapshot, SSE wave/storage stream —
  ``stateright_tpu/telemetry/server.py``), mounted on the same port so
  the UI's dashboard panel needs no second server.

The UI (``stateright_tpu/ui/``) is a small hand-written vanilla-JS page
(the reference uses KnockoutJS; nothing is shared)."""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path as FsPath
from typing import List, Optional

from ..core.fingerprint import fingerprint
from ..core.model import Expectation
from ..core.path import Path
from ..core.visitor import CheckerVisitor
from ..telemetry.server import MonitorCore, handle_monitor_get

_UI_DIR = FsPath(__file__).resolve().parent.parent / "ui"
_SNAPSHOT_RESET_SECONDS = 4.0


class Snapshot(CheckerVisitor):
    """Samples a recent path: keeps the first path seen in each window so the
    status view can show what the checker is working on."""

    def __init__(self, reset_seconds: float = _SNAPSHOT_RESET_SECONDS):
        self._lock = threading.Lock()
        self._path: Optional[Path] = None
        self._stale_at = 0.0
        self._reset_seconds = reset_seconds

    def visit(self, model, path: Path) -> None:
        now = time.monotonic()
        with self._lock:
            if self._path is None or now >= self._stale_at:
                self._path = path
                self._stale_at = now + self._reset_seconds

    def recent_path(self) -> Optional[Path]:
        with self._lock:
            return self._path


# -- view builders (route handlers minus HTTP, exercised directly by tests) --


def status_view(checker, snapshot: Optional[Snapshot] = None,
                monitor: Optional[MonitorCore] = None) -> dict:
    model = checker.model()
    properties = []
    discoveries = checker.discoveries()
    for prop in model.properties():
        found = discoveries.get(prop.name)
        properties.append(
            {
                "name": prop.name,
                "expectation": prop.expectation.value
                if hasattr(prop.expectation, "value")
                else str(prop.expectation),
                "discovery": _encode_path(model, found) if found else None,
            }
        )
    recent = snapshot.recent_path() if snapshot else None
    return {
        "done": checker.is_done(),
        "state_count": checker.state_count(),
        "unique_state_count": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "properties": properties,
        "recent_path": _encode_path(model, recent) if recent else None,
        # The live-monitor progress estimate (same fields as the monitor
        # server's /status): fed by the on-demand checker's block spans
        # when a MonitorCore is attached, null for bare view calls.
        "progress": monitor.estimator.snapshot() if monitor else None,
    }


def _encode_path(model, path: Path) -> dict:
    return {
        "fingerprints": path.encode(),
        "actions": [model.format_action(a) for a in path.into_actions()],
    }


def states_view(checker, fp_path: List[int]) -> dict:
    """The view for ``GET /.states/fp1/fp2/...`` (empty path = init states).

    Raises ``KeyError`` if the path does not replay through the model."""
    model = checker.model()
    if not fp_path:
        states = []
        for state in model.init_states():
            fp = fingerprint(state)
            checker.check_fingerprint(fp)
            states.append(
                {
                    "action": None,
                    "outcome": str(state),
                    "fingerprint": str(fp),
                    "properties": _properties_at(model, state),
                }
            )
        return {"path": "", "svg": None, "next_steps": states}

    replayed = _replay(model, fp_path)
    state = (
        replayed.last_state()
        if replayed is not None
        else Path.final_state(model, fp_path)
    )
    if state is None:
        raise KeyError(
            f"no state matches fingerprint path {'/'.join(map(str, fp_path))}"
        )
    steps = []
    for action, next_state in model.next_steps(state):
        fp = fingerprint(next_state)
        checker.check_fingerprint(fp)
        steps.append(
            {
                "action": model.format_action(action),
                "step": model.format_step(state, action),
                "outcome": str(next_state),
                "fingerprint": str(fp),
                "properties": _properties_at(model, next_state),
            }
        )
    svg = model.as_svg(replayed) if replayed is not None else None
    return {
        "path": "/".join(str(fp) for fp in fp_path),
        "state": str(state),
        "properties": _properties_at(model, state),
        "svg": svg,
        "next_steps": steps,
    }


def _replay(model, fp_path: List[int]) -> Optional[Path]:
    try:
        return Path.from_fingerprints(model, fp_path)
    except RuntimeError:
        return None


def _properties_at(model, state) -> List[dict]:
    out = []
    for prop in model.properties():
        holds = bool(prop.condition(model, state))
        # For an "always" property a False here is a violation; for
        # "sometimes"/"eventually" a True is a witness.
        if prop.expectation == Expectation.ALWAYS:
            status = "ok" if holds else "violated"
        else:
            status = "witnessed" if holds else "pending"
        out.append({"name": prop.name, "holds": holds, "status": status})
    return out


# -- HTTP plumbing -----------------------------------------------------------


def _parse_address(address) -> tuple:
    if isinstance(address, str):
        host, _, port = address.rpartition(":")
        return (host or "localhost", int(port))
    return tuple(address)


_CONTENT_TYPES = {
    ".html": "text/html",
    ".htm": "text/html",
    ".js": "application/javascript",
    ".css": "text/css",
}


def ui_asset(path: str):
    """Resolves a request path against the bundled UI directory:
    ``(content_type, bytes)`` or None (missing file, or a traversal
    attempt outside the UI dir). Shared by the Explorer and the service
    front-end so the traversal guard lives in exactly one place."""
    name = "index.html" if path in ("/", "") else path.lstrip("/")
    file = (_UI_DIR / name).resolve()
    try:
        inside = file.is_relative_to(_UI_DIR)
    except AttributeError:  # Python < 3.9
        import os

        inside = str(file).startswith(str(_UI_DIR) + os.sep)
    if not inside or not file.is_file():
        return None
    return (
        _CONTENT_TYPES.get(file.suffix, "text/plain"),
        file.read_bytes(),
    )


class _Handler(BaseHTTPRequestHandler):
    checker = None
    snapshot = None
    monitor = None

    def log_message(self, *args):  # quiet by default
        pass

    def _json(self, payload, code=200):
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        try:
            # Live-monitor endpoints (/metrics, /status, /events) mount
            # ahead of the Explorer routes and static files.
            if handle_monitor_get(self, self.monitor, self.path):
                return
            if self.path == "/.status":
                self._json(
                    status_view(self.checker, self.snapshot, self.monitor)
                )
            elif self.path.startswith("/.states"):
                raw = [p for p in self.path[len("/.states") :].split("/") if p]
                try:
                    fps = [int(p) for p in raw]
                except ValueError:
                    self._json({"error": "fingerprints must be integers"}, 400)
                    return
                try:
                    self._json(states_view(self.checker, fps))
                except KeyError as e:
                    self._json({"error": str(e)}, 404)
            else:
                self._static(self.path)
        except ConnectionError:
            # Routine client disconnect mid-response (scraper timeout,
            # closed browser tab) must not traceback-spam the server —
            # but only disconnects: a filesystem error in _static must
            # still surface.
            pass

    def do_POST(self):
        if self.path == "/.runtocompletion":
            self.checker.run_to_completion()
            self._json({"ok": True})
        else:
            self._json({"error": "not found"}, 404)

    def _static(self, path: str):
        asset = ui_asset(path)
        if asset is None:
            self._json({"error": "not found"}, 404)
            return
        content_type, body = asset
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class _ExplorerServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that also tears down the attached live-monitor
    core (tracer sink + SSE broker + watchdog) on shutdown, so test and
    embedder lifecycles stay one call."""

    daemon_threads = True
    monitor_core: Optional[MonitorCore] = None

    def shutdown(self):
        if self.monitor_core is not None:
            self.monitor_core.close()
        super().shutdown()


def start_server(builder, address) -> tuple:
    """Spawns the on-demand checker + HTTP server; returns
    ``(server, checker)`` without blocking (used by tests and ``serve``).
    A ``MonitorCore`` rides along, so every Explorer also serves the live
    ``/metrics``, ``/status``, and ``/events`` monitor endpoints."""
    snapshot = Snapshot()
    checker = builder.visitor(snapshot).spawn_on_demand()
    monitor = MonitorCore(checker=checker)
    handler = type(
        "Handler",
        (_Handler,),
        {"checker": checker, "snapshot": snapshot, "monitor": monitor},
    )
    try:
        server = _ExplorerServer(_parse_address(address), handler)
    except BaseException:
        # A failed bind must not leave the core as an orphaned tracer
        # sink overwriting the shared monitor.* gauges forever.
        monitor.close()
        raise
    server.monitor_core = monitor
    thread = threading.Thread(
        target=server.serve_forever, name="explorer-http", daemon=True
    )
    thread.start()
    return server, checker


def serve(builder, address):
    """Blocking entry point used by ``CheckerBuilder.serve``."""
    server, _checker = start_server(builder, address)
    host, port = server.server_address[:2]
    print(f"Exploring state space at http://{host}:{port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()
