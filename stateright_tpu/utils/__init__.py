"""Utility types (reference layer L0, ``/root/reference/src/util*``).

The reference's ``HashableHashSet``/``HashableHashMap`` (order-insensitive
hashing wrappers, ``src/util.rs:73-461``) need no Python counterpart: plain
``frozenset``/``dict`` values are hashed order-insensitively by the stable
fingerprint encoder (``stateright_tpu.core.fingerprint``), and
``utils.rewrite.canonical_sort_key`` provides the deterministic total order
the reference gets from ``Ord``-by-hash.
"""

from .dense_nat_map import DenseNatMap
from .faults import (
    FaultInjector,
    FaultSpec,
    classify_fault,
    fault_point,
    inject,
    seeded_specs,
)
from .rewrite import RewritePlan, canonical_sort_key, rewrite_value
from .vector_clock import VectorClock

__all__ = [
    "DenseNatMap",
    "FaultInjector",
    "FaultSpec",
    "RewritePlan",
    "VectorClock",
    "canonical_sort_key",
    "classify_fault",
    "fault_point",
    "inject",
    "rewrite_value",
    "seeded_specs",
]
