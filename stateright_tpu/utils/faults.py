"""Deterministic seeded fault injection for the self-healing service.

The production failure modes this repo must survive — a host-tier probe
dying mid-wave, a spill hitting ENOSPC, the async pipeline worker
raising, a device wave throwing, a checkpoint write failing, a wedged
wave — are all rare and all timing-shaped, so the chaos tests need a way
to make each of them happen at an EXACT, reproducible point. This module
is that switchboard: code sprinkles zero-cost ``fault_point(site,
tenant=...)`` calls at the interesting seams (``storage/tiered.py``,
``checker/pipeline.py``, ``checker/tpu.py``, ``checker/packed_tenancy
.py``, ``parallel/sharded.py``), and a test arms an injector::

    from stateright_tpu.utils.faults import FaultSpec, inject

    with inject(FaultSpec("storage.host_probe", at=1)):
        ...   # the SECOND host probe anywhere in the process raises
              # HostProbeFault; everything else runs untouched

With no injector installed every ``fault_point`` is one global read and
a None check — the production cost of the whole layer.

Determinism: a spec fires on exact hit indices (``at``/``count``) of a
named site, optionally filtered to one tenant's partition/verdict
(``tenant=``), counted under a lock so multi-threaded engines (the async
pipeline worker, the service scheduler) still hit reproducibly for a
fixed workload. ``seeded_specs`` derives the hit indices from an RNG
seed for randomized-but-replayable chaos sweeps.

Fault taxonomy: every injected exception derives from ``FaultError`` and
carries a ``fault_class`` string; ``classify_fault`` maps ANY exception
(walking the ``__cause__``/``__context__`` chain, so a fault surfaced
through ``PipelinePoisonedError`` or ``TenantFaultError`` still
classifies as its root) to the class string the service's
``RetryPolicy`` filters on.
"""

from __future__ import annotations

import errno
import random
import threading
import time
from contextlib import contextmanager
from typing import Callable, Iterable, List, Optional

__all__ = [
    "CheckpointWriteFault",
    "ConformanceBatchFault",
    "DeviceWaveFault",
    "FaultError",
    "FaultInjector",
    "FaultSpec",
    "HostProbeFault",
    "LivenessEvictFault",
    "PackTenantFault",
    "SpillFault",
    "TenantFaultError",
    "WorkerDeathFault",
    "classify_fault",
    "clear_fault_injector",
    "fault_point",
    "inject",
    "seeded_specs",
    "set_fault_injector",
    "tenant_fault_of",
]


# -- fault taxonomy ----------------------------------------------------------


class FaultError(Exception):
    """Base class for injected faults. ``fault_class`` is the string the
    service's retry filter and the ``fault.*`` metrics key on."""

    fault_class = "unknown"


class HostProbeFault(FaultError):
    """An L1/L2 host-tier probe died mid-wave."""

    fault_class = "host_probe"


class SpillFault(OSError, FaultError):
    """A spill write hit the disk (injected as ENOSPC, the classic)."""

    fault_class = "spill"

    def __init__(self, msg: str = "No space left on device (injected)"):
        OSError.__init__(self, errno.ENOSPC, msg)


class WorkerDeathFault(FaultError):
    """The async host-pipeline worker died mid-job."""

    fault_class = "pipeline_worker"


class DeviceWaveFault(FaultError):
    """A device wave dispatch raised (XLA error, OOM, tunnel drop)."""

    fault_class = "device_wave"


class CheckpointWriteFault(FaultError):
    """A checkpoint pickle/rename failed."""

    fault_class = "checkpoint_write"


class PackTenantFault(FaultError):
    """A per-tenant slice of packed host work (verdict/evict) raised."""

    fault_class = "pack_tenant"


class LivenessEvictFault(FaultError):
    """A liveness edge-store eviction absorb died mid-run (device pull,
    numpy OOM, spill)."""

    fault_class = "liveness_evict"


class SeedLoadFault(OSError, FaultError):
    """A warm-start seed artifact read died (torn file, failing disk) —
    the honest outcome is a refused seed and a full recheck."""

    fault_class = "seed_load"


class ConformanceBatchFault(FaultError):
    """A conformance batch dispatch raised (replay/audit kernel, XLA
    error). Verdicts are deterministic in the upload, so a retry must
    recover bit-identically through the journal."""

    fault_class = "conformance_batch"


class TenantFaultError(Exception):
    """An engine fault attributable to exactly ONE packed tenant — the
    pack's blast-radius boundary. The service drops only this tenant
    (its rolled-back checkpoint-v2 payload slice rides the retry) while
    the surviving tenants keep expanding. ``pre_dispatch=True`` means
    the wave never executed, so EVERY participant's input lanes were
    restored (not just the faulted tenant's)."""

    def __init__(self, tenant_key, original: BaseException,
                 pre_dispatch: bool = False):
        super().__init__(
            f"fault attributable to packed tenant {tenant_key!r}: "
            f"{original!r}"
        )
        self.tenant_key = tenant_key
        self.original = original
        self.pre_dispatch = pre_dispatch


def _exception_chain(exc: Optional[BaseException]):
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        yield exc
        exc = exc.__cause__ or exc.__context__


def classify_fault(exc: Optional[BaseException]) -> str:
    """The fault-class string for an arbitrary exception: the first
    ``FaultError`` (or recognizable real-world analogue) in its cause
    chain, else ``"unknown"``. This is what ``RetryPolicy.retry_on``
    filters against, so injected and organic faults classify alike."""
    from ..checker.pipeline import PipelinePoisonedError

    saw_pipeline = False
    for e in _exception_chain(exc):
        if isinstance(e, TenantFaultError):
            e = e.original
        if isinstance(e, FaultError):
            return e.fault_class
        if isinstance(e, OSError) and e.errno == errno.ENOSPC:
            return "spill"
        if isinstance(e, PipelinePoisonedError):
            saw_pipeline = True
    return "pipeline_worker" if saw_pipeline else "unknown"


def tenant_fault_of(exc: Optional[BaseException]):
    """The ``TenantFaultError`` in an exception's cause chain, or None —
    how the service decides whether a pack fault is attributable to one
    tenant (drop its lanes) or to the whole engine (retry all solo)."""
    for e in _exception_chain(exc):
        if isinstance(e, TenantFaultError):
            return e
    return None


# -- the injector ------------------------------------------------------------

# Default exception factory per site (a spec may override with exc=).
_SITE_EXC = {
    "storage.host_probe": HostProbeFault,
    "storage.spill": SpillFault,
    "pipeline.worker": WorkerDeathFault,
    "device.wave": DeviceWaveFault,
    "checkpoint.write": CheckpointWriteFault,
    "pack.tenant.verdict": PackTenantFault,
    "pack.tenant.evict": PackTenantFault,
    "liveness.edge_evict": LivenessEvictFault,
    # Swarm engine seams (checker/swarm.py): the stacked wave dispatch
    # and the per-tenant harvest that bounds a packed swarm's blast
    # radius.
    "swarm.wave": DeviceWaveFault,
    "swarm.tenant.verdict": PackTenantFault,
    # Warm-start plane (storage/persist.py): the seed-artifact read —
    # refusal must degrade to a full recheck, never a wrong verdict.
    "warmstart.seed_load": SeedLoadFault,
    # Conformance plane (conformance/checker.py): the per-batch device
    # dispatch — the retry seam for uploaded-trace auditing.
    "conformance.batch": ConformanceBatchFault,
}

# Sites that exist in the tree — fail fast on typos in test specs.
FAULT_SITES = frozenset(_SITE_EXC) | {"wave.stall"}


class FaultSpec:
    """One planned fault: fire at hit indices ``[at, at + count)`` of
    ``site`` (0-based, counted per spec over the hits that match its
    ``tenant`` filter). ``stall_s`` sleeps instead of raising (the
    wedged-wave simulation the stall watchdog must catch); ``exc`` is a
    zero-arg exception factory overriding the site default."""

    def __init__(self, site: str, at: int = 0, count: int = 1,
                 tenant=None, exc: Optional[Callable] = None,
                 stall_s: Optional[float] = None):
        if site not in FAULT_SITES:
            raise ValueError(
                f"unknown fault site {site!r} (known: {sorted(FAULT_SITES)})"
            )
        if site == "wave.stall" and stall_s is None:
            raise ValueError("site 'wave.stall' needs stall_s=")
        self.site = site
        self.at = int(at)
        self.count = max(1, int(count))
        self.tenant = tenant
        self.exc = exc if exc is not None else _SITE_EXC.get(site)
        self.stall_s = stall_s
        self.hits = 0       # matching fault_point calls seen
        self.triggered = 0  # times this spec actually fired

    def __repr__(self):
        return (
            f"FaultSpec({self.site!r}, at={self.at}, count={self.count}, "
            f"tenant={self.tenant!r}, hits={self.hits}, "
            f"triggered={self.triggered})"
        )


class FaultInjector:
    """Thread-safe deterministic fault plan: counts every matching
    ``fault_point`` hit per spec and fires on the planned indices."""

    def __init__(self, *specs: FaultSpec):
        self._specs: List[FaultSpec] = list(specs)
        self._lock = threading.Lock()

    @property
    def specs(self) -> List[FaultSpec]:
        return list(self._specs)

    def triggered(self, site: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                s.triggered
                for s in self._specs
                if site is None or s.site == site
            )

    def hits(self, site: str) -> int:
        with self._lock:
            return max(
                (s.hits for s in self._specs if s.site == site), default=0
            )

    def fire(self, site: str, tenant=None) -> None:
        stall = None
        trip: Optional[FaultSpec] = None
        with self._lock:
            for spec in self._specs:
                if spec.site != site:
                    continue
                if spec.tenant is not None and spec.tenant != tenant:
                    continue
                idx = spec.hits
                spec.hits += 1
                if spec.at <= idx < spec.at + spec.count:
                    spec.triggered += 1
                    if spec.stall_s is not None:
                        stall = spec.stall_s
                    else:
                        trip = spec
                    break
        if stall is not None:
            self._count_metric(site)
            time.sleep(stall)
            return
        if trip is not None:
            self._count_metric(site)
            raise trip.exc()

    @staticmethod
    def _count_metric(site: str) -> None:
        # Observable injection evidence (never load-bearing): the chaos
        # CI job asserts the fault actually fired via this counter.
        try:
            from ..telemetry import metrics_registry

            reg = metrics_registry()
            reg.counter("fault.injected").inc()
            reg.counter(f"fault.injected.{site}").inc()
        except Exception:  # noqa: BLE001 - diagnostics only
            pass


_ACTIVE: Optional[FaultInjector] = None
_ACTIVE_LOCK = threading.Lock()


def set_fault_injector(inj: Optional[FaultInjector]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = inj


def clear_fault_injector() -> None:
    set_fault_injector(None)


def fault_point(site: str, tenant=None) -> None:
    """An injection seam. One global load + None check when no injector
    is armed — safe on every hot path it decorates."""
    inj = _ACTIVE
    if inj is not None:
        inj.fire(site, tenant=tenant)


@contextmanager
def inject(*specs: FaultSpec):
    """Arms a process-wide injector for the with-block (tests). Nested
    injection is a test bug — refused rather than silently merged."""
    with _ACTIVE_LOCK:
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("a fault injector is already installed")
        inj = FaultInjector(*specs)
        _ACTIVE = inj
    try:
        yield inj
    finally:
        clear_fault_injector()


def seeded_specs(seed: int, sites: Iterable[str], max_at: int = 8,
                 ) -> List[FaultSpec]:
    """A reproducible randomized plan: one fault per site at an RNG-drawn
    hit index. Same seed → same plan → same failure point, run after
    run — the 'deterministic seeded' half of the chaos harness."""
    rng = random.Random(seed)
    return [
        FaultSpec(site, at=rng.randrange(max(1, max_at)))
        for site in sites
    ]
