"""Symmetry-reduction machinery: rewrite plans and recursive Id rewriting.

A ``RewritePlan`` is a permutation derived from sorting a state's per-actor
rows; applying it recursively yields a behaviorally equivalent state — the
canonical representative of the symmetry equivalence class.

Reference: ``RewritePlan`` at ``/root/reference/src/checker/rewrite_plan.rs``
(permutation-by-sorting at ``:81-106``, ``reindex`` at ``:110-123``) and the
recursive ``Rewrite`` impls at ``/root/reference/src/checker/rewrite.rs``.

On the TPU backend the representative computation is a vmapped argsort over
packed per-actor state rows plus an Id gather (``stateright_tpu.ops``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Sequence

from ..core.fingerprint import stable_encode


def canonical_sort_key(value) -> bytes:
    """A deterministic total-order key for arbitrary stable-hashable values:
    the canonical byte encoding (the reference requires ``V: Ord``; mixing
    natural ordering with a hash fallback would be intransitive for
    heterogeneous values, so the encoding alone is the order).

    Any deterministic total order yields a valid canonicalization — the set of
    equivalence classes (and hence symmetry-reduced state counts) does not
    depend on which member is chosen as representative."""
    return stable_encode(value)


def orbit_min(n: int, permuted_fn: Callable):
    """True orbit canonical form: the minimum over all ``n!`` rewrite plans
    of ``permuted_fn(plan)``, keyed by canonical byte encoding. Proper (one
    representative per orbit), so symmetry-reduced counts are traversal- and
    engine-independent — the host twin of the device checkers'
    minimum-fingerprint symmetry key. Shares the device path's actor-count
    bound (``n!`` group enumeration)."""
    from itertools import permutations

    from ..core.batch import MAX_SYMMETRY_ACTORS

    if n > MAX_SYMMETRY_ACTORS:
        raise ValueError(
            f"orbit canonicalization over {n} actors enumerates {n}! "
            f"permutations; the supported bound is {MAX_SYMMETRY_ACTORS}"
        )
    return min(
        (permuted_fn(RewritePlan(list(p))) for p in permutations(range(n))),
        key=canonical_sort_key,
    )


class RewritePlan:
    """Maps old actor indices (Ids) to new ones."""

    def __init__(self, mapping: List[int]):
        # mapping[old_index] = new_index
        self.mapping = mapping

    @staticmethod
    def from_values_to_sort(values: Sequence) -> "RewritePlan":
        """Builds the permutation that stable-sorts ``values``."""
        order = sorted(range(len(values)), key=lambda i: canonical_sort_key(values[i]))
        mapping = [0] * len(values)
        for new_index, old_index in enumerate(order):
            mapping[old_index] = new_index
        return RewritePlan(mapping)

    def rewrite_id(self, id_value):
        from ..actor.actor import Id

        return Id(self.mapping[int(id_value)])

    def reindex(self, indexed: Sequence) -> List:
        """Permutes a per-actor vector (result[new] = rewrite(indexed[old]))
        and recursively rewrites each element."""
        result = [None] * len(self.mapping)
        for old_index, new_index in enumerate(self.mapping):
            result[new_index] = rewrite_value(indexed[old_index], self)
        return result


def rewrite_value(value, plan: RewritePlan):
    """Recursively rewrites every ``Id`` inside ``value`` per ``plan``.

    Only instances of ``stateright_tpu.actor.Id`` are rewritten; plain ints
    pass through (mirroring the reference where only the ``Id`` type
    implements ``Rewrite<Id>`` non-trivially)."""
    from ..actor.actor import Id

    if isinstance(value, Id):
        return plan.rewrite_id(value)
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return value
    if isinstance(value, tuple):
        return tuple(rewrite_value(v, plan) for v in value)
    if isinstance(value, list):
        return [rewrite_value(v, plan) for v in value]
    if isinstance(value, (set, frozenset)):
        return frozenset(rewrite_value(v, plan) for v in value)
    if isinstance(value, dict):
        return {
            rewrite_value(k, plan): rewrite_value(v, plan)
            for k, v in value.items()
        }
    if hasattr(value, "__rewrite__"):
        return value.__rewrite__(plan)
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return type(value)(
            **{
                f.name: rewrite_value(getattr(value, f.name), plan)
                for f in dataclasses.fields(value)
            }
        )
    # Opaque values (e.g. Timers) are returned unchanged.
    return value
