"""Shared persistent-compilation-cache setup.

One definition of the cache location, used by tests/conftest.py,
scripts/cpu_pin.py, and bench.py's per-leg subprocesses — a split cache
silently loses the cross-run hits the warmup accounting depends on. The
directory is per-uid (shared hosts must not collide on a world-writable
path), and entries key on the HLO hash, so source changes miss naturally.
"""

from __future__ import annotations

import os
import tempfile


def cache_dir() -> str:
    return os.path.join(
        tempfile.gettempdir(), f"jax_comp_cache_{os.getuid()}"
    )


def enable_persistent_cache() -> None:
    """Call after importing jax (and after any platform re-pin)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
