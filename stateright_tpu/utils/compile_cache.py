"""Shared persistent-compilation-cache setup.

One definition of the cache location, used by tests/conftest.py,
scripts/cpu_pin.py, and bench.py's per-leg subprocesses — a split cache
silently loses the cross-run hits the warmup accounting depends on.

Two hazards shape the location:

- **Target mismatch.** XLA *loads* cached CPU executables even when the
  recorded feature set differs from the host's — it warns ("could lead
  to execution errors such as SIGILL", seen in BENCH_r03.json's tail)
  rather than rejecting, verified empirically in round 4: a store-then-
  load on the SAME box with the SAME pinning still warns, because the
  recorded features include XLA compile *preferences*
  (``+prefer-no-scatter``/``+prefer-no-gather``) that the host feature
  probe never lists. Two consequences: (a) the r03 warning itself is a
  benign false alarm inherent to every warm CPU cache load on this XLA
  build — it cannot be silenced without forfeiting the CPU cache; (b)
  the loader provides NO real cross-target protection, so protection
  must come from the directory key. The directory is therefore scoped
  by a fingerprint of the host CPU features AND the resolved JAX
  platform line-up: artifacts compiled through the device tunnel
  (platforms=axon,cpu) and CPU-pinned artifacts (platforms=cpu) never
  share a key, and a different machine's CPU artifacts land elsewhere.
  Callers must enable the cache AFTER any platform re-pin so the tag
  sees the resolved line-up (tests/conftest.py, scripts/cpu_pin.py,
  bench.py all do).
- **Cache poisoning.** A world-readable predictable path under /tmp lets
  another local user pre-create the directory and plant compiled
  executables the victim will load. The cache now lives under the user's
  home with mode 0700, and ``enable_persistent_cache`` verifies
  ownership before handing the path to JAX (falling back to disabling
  the persistent cache rather than loading untrusted artifacts).

Entries key on the HLO hash within the directory, so source changes miss
naturally.
"""

from __future__ import annotations

import hashlib
import os
import platform as _platform
import sys


def _target_tag(platforms: str | None = None) -> str:
    """Fingerprint of (machine arch, host CPU feature flags, requested
    JAX platforms). Order-insensitive on the flags; stable across runs on
    the same box with the same platform pin. ``platforms`` overrides
    detection (tests)."""
    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 exposes "flags", aarch64 "Features".
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    if platforms is None:
        platforms = os.environ.get("JAX_PLATFORMS", "")
        try:
            import jax

            platforms = jax.config.jax_platforms or platforms
        except Exception:
            pass
    key = f"{_platform.machine()}|{feats}|{platforms}"
    return hashlib.blake2s(key.encode(), digest_size=8).hexdigest()


def cache_dir() -> str:
    return os.path.join(
        os.path.expanduser("~"),
        ".cache",
        "stateright_tpu",
        f"jax_comp_cache_{_target_tag()}",
    )


def enable_persistent_cache() -> None:
    """Call after importing jax (and after any platform re-pin — the
    platform is part of the cache key)."""
    import jax

    d = cache_dir()
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
        os.chmod(d, 0o700)
        st = os.stat(d)
        owned = st.st_uid == os.getuid()
    except OSError as e:
        # chmod on a dir owned by someone else raises EPERM before the
        # ownership check ever runs (the pre-created-dir poisoning case),
        # and an unwritable $HOME fails makedirs — both take the disable
        # path rather than killing the caller or loading untrusted
        # artifacts.
        print(
            f"compile_cache: cannot secure {d} ({e}); "
            "persistent cache DISABLED",
            file=sys.stderr,
        )
        return
    if not owned:
        print(
            f"compile_cache: {d} not owned by uid {os.getuid()}; "
            "persistent cache DISABLED",
            file=sys.stderr,
        )
        return
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
