"""A map from dense natural-number keys (e.g. actor ``Id``s) to values.

Semantics mirror the reference (``/root/reference/src/util/densenatmap.rs``):
keys must stay dense — ``insert`` either overwrites an existing key or
appends at exactly ``len`` (anything else raises), which catches actor-index
bookkeeping bugs early. Symmetry reduction reindexes the map through the
rewrite plan (reference ``Rewrite`` impl at ``:223-236``).
"""

from __future__ import annotations

from typing import Generic, Iterable, Iterator, List, Tuple, TypeVar

V = TypeVar("V")


class DenseNatMap(Generic[V]):
    __slots__ = ("_values",)

    def __init__(self, values: Iterable[V] = ()):
        self._values: List[V] = list(values)

    @staticmethod
    def from_pairs(pairs: Iterable[Tuple[int, V]]) -> "DenseNatMap":
        """Builds from (key, value) pairs in any order; the keys must form
        exactly ``0..n``."""
        pairs = list(pairs)
        result: List = [None] * len(pairs)
        seen = [False] * len(pairs)
        for k, v in pairs:
            k = int(k)
            if not 0 <= k < len(pairs) or seen[k]:
                raise ValueError(
                    f"keys must form a dense range 0..{len(pairs)}: "
                    f"bad or duplicate key {k}"
                )
            seen[k] = True
            result[k] = v
        return DenseNatMap(result)

    def get(self, key) -> V:
        index = int(key)
        if not 0 <= index < len(self._values):
            return None
        return self._values[index]

    def insert(self, key, value: V) -> V:
        """Overwrites ``key`` (returning the previous value) or appends at
        exactly ``len`` (returning None). Out-of-order inserts raise."""
        index = int(key)
        if index > len(self._values):
            raise IndexError(
                f"out-of-order insert: index={index}, len={len(self._values)}"
            )
        if index == len(self._values):
            self._values.append(value)
            return None
        previous, self._values[index] = self._values[index], value
        return previous

    def __getitem__(self, key) -> V:
        return self._values[int(key)]

    def __setitem__(self, key, value: V) -> None:
        self.insert(key, value)

    def __contains__(self, key) -> bool:
        return 0 <= int(key) < len(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def __iter__(self) -> Iterator[V]:
        return iter(self._values)

    def values(self) -> List[V]:
        return list(self._values)

    def items(self):
        from ..actor.actor import Id

        return [(Id(i), v) for i, v in enumerate(self._values)]

    def __eq__(self, other) -> bool:
        if not isinstance(other, DenseNatMap):
            return NotImplemented
        return self._values == other._values

    def __hash__(self) -> int:
        from ..core.fingerprint import stable_hash

        return stable_hash(tuple(self._values))

    def __stable_fields__(self):
        return (tuple(self._values),)

    def __rewrite__(self, plan) -> "DenseNatMap":
        return DenseNatMap(plan.reindex(self._values))

    def __repr__(self) -> str:
        return f"DenseNatMap({self._values!r})"
