"""Vector clocks: a partial causal order on distributed events.

Semantics mirror the reference (``/root/reference/src/util/vector_clock.rs``):
implicit-zero padding for equality and ordering, zero-truncating stable hash
(so ``[1]`` and ``[1, 0]`` are equal and hash identically), and elementwise
max merge. Instances are immutable — operations return new clocks — which
matches this framework's value-style state discipline.
"""

from __future__ import annotations

from typing import Iterable, Tuple


class VectorClock:
    __slots__ = ("_elems",)

    def __init__(self, elems: Iterable[int] = ()):
        self._elems: Tuple[int, ...] = tuple(int(e) for e in elems)

    def elems(self) -> Tuple[int, ...]:
        return self._elems

    def incremented(self, index: int) -> "VectorClock":
        """A copy with component ``index`` incremented (growing as needed)."""
        elems = list(self._elems)
        if index >= len(elems):
            elems.extend([0] * (1 + index - len(elems)))
        elems[index] += 1
        return VectorClock(elems)

    @staticmethod
    def merge_max(c1: "VectorClock", c2: "VectorClock") -> "VectorClock":
        """Elementwise max of two clocks."""
        n = max(len(c1._elems), len(c2._elems))
        return VectorClock(
            max(c1._get(i), c2._get(i)) for i in range(n)
        )

    def _get(self, i: int) -> int:
        return self._elems[i] if i < len(self._elems) else 0

    def _truncated(self) -> Tuple[int, ...]:
        cutoff = len(self._elems)
        while cutoff and self._elems[cutoff - 1] == 0:
            cutoff -= 1
        return self._elems[:cutoff]

    # Trailing zeros are semantically absent: equality/hash/order all pad
    # with implicit zeros.

    def __eq__(self, other) -> bool:
        if not isinstance(other, VectorClock):
            return NotImplemented
        return self._truncated() == other._truncated()

    def __hash__(self) -> int:
        return hash(self._truncated())

    def __stable_fields__(self):
        return (self._truncated(),)

    def _cmp(self, other) -> object:
        """-1/0/1 for ordered clocks, None for concurrent (incomparable)."""
        expected = 0
        for i in range(max(len(self._elems), len(other._elems))):
            a, b = self._get(i), other._get(i)
            order = (a > b) - (a < b)
            if expected == 0:
                expected = order
            elif order not in (0, expected):
                return None
        return expected

    def __lt__(self, other) -> bool:
        return self._cmp(other) == -1

    def __le__(self, other) -> bool:
        return self._cmp(other) in (-1, 0)

    def __gt__(self, other) -> bool:
        return self._cmp(other) == 1

    def __ge__(self, other) -> bool:
        return self._cmp(other) in (0, 1)

    def concurrent_with(self, other) -> bool:
        """True when neither clock happened-before the other."""
        return self._cmp(other) is None

    def __repr__(self) -> str:
        return f"VectorClock({list(self._elems)!r})"

    def __str__(self) -> str:
        return "<" + "".join(f"{c}, " for c in self._elems) + "...>"
