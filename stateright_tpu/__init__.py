"""stateright_tpu: TPU-native explicit-state model checking of distributed systems.

A brand-new framework with the capabilities of the Rust `stateright` library
(reference at /root/reference), re-designed TPU-first: frontier expansion runs
as vmapped JAX kernels, the visited set is a device-resident hash over stable
64-bit fingerprints, and property predicates evaluate over state batches.

Public API mirrors the reference's compatibility surface:

    from stateright_tpu import Model, Property
    checker = MyModel().checker().threads(4).spawn_bfs().join()
    checker.assert_properties()
"""

from .core.fingerprint import Fingerprint, fingerprint, stable_hash
from .core.model import Expectation, FnModel, Model, Property
from .core.path import Path
from .core.visitor import CheckerVisitor, FnVisitor, PathRecorder, StateRecorder
from .checker.base import Checker
from .checker.builder import CheckerBuilder
from .report import (
    ReportData,
    ReportDiscovery,
    Reporter,
    TelemetryReporter,
    WriteReporter,
)
from .telemetry import get_tracer, metrics_registry

__version__ = "0.1.0"

__all__ = [
    "Checker",
    "CheckerBuilder",
    "CheckerVisitor",
    "Expectation",
    "Fingerprint",
    "FnModel",
    "FnVisitor",
    "Model",
    "Path",
    "PathRecorder",
    "Property",
    "ReportData",
    "ReportDiscovery",
    "Reporter",
    "StateRecorder",
    "TelemetryReporter",
    "WriteReporter",
    "fingerprint",
    "get_tracer",
    "metrics_registry",
    "stable_hash",
]
