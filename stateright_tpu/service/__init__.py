"""Checking-as-a-service: a persistent, in-process multi-tenant check
scheduler over the device checkers.

The AOT wave cache is keyed on ``(bucket, table_capacity)`` under a
model-config signature, so one resident process can serve many models and
many requests without ever recompiling a wave shape it has already built
— the "serve heavy traffic" shape from the ROADMAP north star, and the
same single-device utilization problem GPUexplore solves inside one GPU
(PAPERS: "On the Scalability of the GPUexplore Explicit-State Model
Checker"). Three layers:

- :class:`CheckService` — owns the device: an admission queue of
  :class:`CheckJob` s (model + options + per-tenant ``hbm_budget_mib`` /
  deadline / priority) and a scheduler loop. Qualifying same-shape jobs
  are PACKED into shared physical waves (tenant-salted fingerprints in
  one visited table, per-lane tenant ids — ``checker/packed_tenancy``),
  so concurrency costs ~nothing and preemption is a lane drop; the rest
  time-slice the device at wave granularity through the checkpoint-v2
  preempt/resume machinery (``TpuBfsChecker.request_preempt`` drains a
  job's wave state to a host-side payload; resuming it later is
  bit-identical to an uninterrupted run). ``submit()`` returns a
  :class:`JobHandle` (``result()`` / ``status()`` / ``cancel()``).
- :class:`ServiceServer` — the HTTP front-end (``POST /jobs`` against
  the registered model zoo, ``GET /jobs``, ``GET /jobs/<id>``,
  per-job ``/jobs/<id>/metrics``, the aggregate live-monitor endpoints,
  and the Explorer UI page with the job-list panel).
- ``bench.py --service`` — the latency-oriented bench legs (p50/p99
  time-to-first-violation and aggregate states/s under concurrent load;
  ``scripts/service_report.py`` renders the records).

Per-job telemetry rides the run-scoped plumbing: each job gets its own
``run_id`` (own metrics registry, stamped trace spans), so ``/metrics``,
``/status``, SSE, attribution, and coverage all work per job.
"""

from .jobs import (
    JOB_CANCELLED,
    JOB_DONE,
    JOB_FAILED,
    JOB_FAULTED,
    JOB_QUARANTINED,
    JOB_QUEUED,
    JOB_RUNNING,
    JOB_SUSPENDED,
    CheckJob,
    JobHandle,
    RetryPolicy,
)
from .service import CheckService, QueueFullError
from .zoo import default_zoo

# ServiceServer drags in http.server; resolve lazily (PEP 562) like the
# telemetry package does for MonitorServer.
_HTTP_SYMBOLS = frozenset({"ServiceServer"})


def __getattr__(name):
    if name in _HTTP_SYMBOLS:
        from . import http

        return getattr(http, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "CheckJob",
    "CheckService",
    "JobHandle",
    "JOB_CANCELLED",
    "JOB_DONE",
    "JOB_FAILED",
    "JOB_FAULTED",
    "JOB_QUARANTINED",
    "JOB_QUEUED",
    "JOB_RUNNING",
    "JOB_SUSPENDED",
    "QueueFullError",
    "RetryPolicy",
    "ServiceServer",
    "default_zoo",
]
