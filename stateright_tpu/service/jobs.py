"""Job objects for the check service: the admission-queue entry and the
caller-facing handle.

A job's lifecycle::

    queued ──schedule──▶ running ──complete──▶ done
       ▲                    │ │────fail──────▶ failed
       │                    │ │────cancel────▶ cancelled
       └─────suspended ◀────┘ (preempted at a wave boundary; the
             checkpoint payload re-enters the queue)

All mutation happens on the scheduler thread; readers (``status()``, the
HTTP front-end) take the job lock only for the multi-field snapshots so a
mid-transition read never mixes two states' fields.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

JOB_QUEUED = "queued"
JOB_RUNNING = "running"
JOB_SUSPENDED = "suspended"
JOB_DONE = "done"
JOB_FAILED = "failed"
JOB_CANCELLED = "cancelled"

_TERMINAL = (JOB_DONE, JOB_FAILED, JOB_CANCELLED)


class CheckJob:
    """One submitted check: the model factory + builder options + spawn
    kwargs, the tenant's scheduling class (``priority`` high-first,
    ``deadline_s`` earliest-first within a priority, FIFO within a
    deadline), the per-tenant ``hbm_budget_mib``, and the run state the
    scheduler threads through preempt/resume cycles."""

    def __init__(
        self,
        job_id: str,
        model_factory: Callable,
        *,
        model_name: Optional[str] = None,
        options: Optional[dict] = None,
        spawn: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        hbm_budget_mib: Optional[float] = None,
        aot_namespace: Optional[str] = None,
        seq: int = 0,
        clock=time.monotonic,
    ):
        self.job_id = job_id
        self.run_id = job_id
        self.model_factory = model_factory
        self.model_name = model_name
        self.options = dict(options or {})
        self.spawn = dict(spawn or {})
        self.priority = int(priority)
        self.deadline_s = deadline_s
        self.tenant = tenant
        self.hbm_budget_mib = hbm_budget_mib
        self.aot_namespace = aot_namespace
        self.seq = seq
        self._clock = clock
        self._lock = threading.Lock()

        self.state = JOB_QUEUED
        self.payload: Optional[dict] = None  # suspended checkpoint
        self.result: Optional[dict] = None
        self.error: Optional[str] = None
        self.preempts = 0
        self.slices = 0
        # Honest backend surfacing (the service fills these at admission
        # and corrects them from the live checker): ``preemptible`` —
        # the spawn method yields resumable preempt payloads (a False
        # here means this job SERIALIZES the device for its whole run);
        # ``packable`` — the job qualifies for tenant-packed waves
        # (``packable_reason`` says why not); ``packed`` — it actually
        # ran co-scheduled in at least one pack.
        self.preemptible: Optional[bool] = None
        self.packable = False
        self.packable_reason: Optional[str] = None
        self.packed = False
        # Budget-derived device table sizing (None = service default).
        self.derived_table_capacity: Optional[int] = None
        # Pack-membership clock: join time of the current packed slice.
        self.pack_join_t: Optional[float] = None
        self.active_s = 0.0  # device-holding wall across slices
        self.warmup_s = 0.0  # summed compile warmup across incarnations
        self.submitted_t = clock()
        # Round-robin clock: a slice bumps it, so within one
        # (priority, deadline) class the scheduler always picks the
        # least-recently-run job — preempting a job only to re-pick it
        # would be pure checkpoint/restore churn.
        self.last_run_t = self.submitted_t
        self.started_t: Optional[float] = None
        self.first_discovery_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.seen_discoveries: set = set()
        self.cancel_event = threading.Event()
        self.done_event = threading.Event()

    # -- scheduler-side helpers --------------------------------------------

    def sort_key(self, last_run_override=None):
        """Admission order: priority high-first, then earliest absolute
        deadline, then round-robin (least recently run; FIFO among
        never-run jobs, whose clock is their submission time).
        ``last_run_override`` evaluates the key as if the job had just
        run — the quantum-expiry preemption test compares peers against
        the running job's REENTRY position with this, so the two always
        use one key shape."""
        deadline = (
            self.submitted_t + self.deadline_s
            if self.deadline_s is not None
            else float("inf")
        )
        last_run = (
            self.last_run_t if last_run_override is None else last_run_override
        )
        return (-self.priority, deadline, last_run, self.seq)

    def runnable(self) -> bool:
        return self.state in (JOB_QUEUED, JOB_SUSPENDED)

    def finish(self, state: str) -> None:
        with self._lock:
            self.state = state
            self.finished_t = self._clock()
        self.done_event.set()

    # State transitions take the job lock so a concurrent status() never
    # reads mixed fields (e.g. state "running" with a verdict attached).

    def suspend(self, payload: dict) -> None:
        with self._lock:
            self.payload = payload
            self.preempts += 1
            self.state = JOB_SUSPENDED

    def complete(self, result: dict) -> None:
        # Verdict and terminal state land under ONE lock acquisition —
        # a reader must never see state "running" with a result attached.
        with self._lock:
            self.result = result
            self.state = JOB_DONE
            self.finished_t = self._clock()
        self.done_event.set()

    def fail(self, error: str) -> None:
        with self._lock:
            self.error = error
            self.payload = None
            self.state = JOB_FAILED
            self.finished_t = self._clock()
        self.done_event.set()

    # -- views --------------------------------------------------------------

    def latency(self) -> Dict[str, Optional[float]]:
        """The latency block every status/bench record carries:
        ``queued_s`` (submit -> first schedule), ``ttfv_s`` (submit ->
        first property discovery — time-to-first-violation for
        falsifiable workloads, time-to-first-witness for ``sometimes``),
        ``wall_s`` (submit -> terminal state, live runs: so far), and
        ``active_s`` (device-holding time across slices)."""
        now = self._clock()
        end = self.finished_t if self.finished_t is not None else now
        return {
            # A never-scheduled job's queue wait ends at its terminal
            # time (a cancelled-while-queued job must not report a
            # forever-growing queued_s).
            "queued_s": (self.started_t or end) - self.submitted_t,
            "ttfv_s": (
                self.first_discovery_t - self.submitted_t
                if self.first_discovery_t is not None
                else None
            ),
            "wall_s": end - self.submitted_t,
            "active_s": self.active_s,
        }

    def status(self) -> dict:
        with self._lock:
            out = {
                "job_id": self.job_id,
                "run_id": self.run_id,
                "model": self.model_name,
                "tenant": self.tenant,
                "priority": self.priority,
                "deadline_s": self.deadline_s,
                "hbm_budget_mib": self.hbm_budget_mib,
                "state": self.state,
                "preemptible": self.preemptible,
                "packable": self.packable,
                "packable_reason": self.packable_reason,
                "packed": self.packed,
                "preempts": self.preempts,
                "slices": self.slices,
                "discoveries_so_far": sorted(self.seen_discoveries),
                "latency": self.latency(),
                "result": self.result,
                "error": self.error,
            }
        return out

    # The scalar result fields the job-list view keeps; the heavy ones
    # (golden report text, attribution/coverage ledgers, per-discovery
    # detail) stay on the single-job view.
    _SUMMARY_RESULT_FIELDS = (
        "unique", "states", "max_depth", "properties_hold", "rate",
    )

    def summary(self) -> dict:
        """``status()`` minus the heavy result payload — what the
        ``GET /jobs`` listing (polled every ~2s by the UI panel)
        actually renders. Full verdicts stay on ``GET /jobs/<id>``."""
        out = self.status()
        result = out.get("result")
        if isinstance(result, dict):
            out["result"] = {
                k: result.get(k) for k in self._SUMMARY_RESULT_FIELDS
            }
        return out


class JobHandle:
    """The caller's view of a submitted job (the Python-API surface the
    HTTP front-end mirrors)."""

    def __init__(self, job: CheckJob, service):
        self._job = job
        self._service = service

    @property
    def job_id(self) -> str:
        return self._job.job_id

    def done(self) -> bool:
        return self._job.done_event.is_set()

    def status(self) -> dict:
        return self._job.status()

    def cancel(self) -> bool:
        """Requests cancellation; True unless the job already reached a
        terminal state. A running job is preempted at its next wave
        boundary and its payload discarded."""
        if self._job.state in _TERMINAL:
            return False
        self._job.cancel_event.set()
        self._service._wake()
        return True

    def result(self, timeout: Optional[float] = None) -> dict:
        """Blocks for the verdict. Raises ``TimeoutError`` on timeout,
        ``RuntimeError`` for a failed or cancelled job."""
        if not self._job.done_event.wait(timeout):
            raise TimeoutError(
                f"job {self._job.job_id} not done within {timeout}s"
            )
        if self._job.state == JOB_FAILED:
            raise RuntimeError(
                f"job {self._job.job_id} failed: {self._job.error}"
            )
        if self._job.state == JOB_CANCELLED:
            raise RuntimeError(f"job {self._job.job_id} was cancelled")
        return self._job.result
