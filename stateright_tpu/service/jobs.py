"""Job objects for the check service: the admission-queue entry and the
caller-facing handle.

A job's lifecycle::

    queued ──schedule──▶ running ──complete──▶ done
       ▲                  │ │ │────fail───────▶ failed
       │                  │ │ │────cancel─────▶ cancelled
       │                  │ │ └───fault───▶ faulted ──backoff──▶ (requeue)
       │                  │ │                  └─retries exhausted─▶
       │                  │ │                               quarantined
       └─────suspended ◀──┘ (preempted at a wave boundary; the
             checkpoint payload re-enters the queue)

``faulted`` is the self-healing state: a slice died (host probe, spill,
device wave, pipeline worker, checkpoint write — see
``utils/faults.classify_fault``), the scheduler harvested the best
checkpoint payload it could (the job's pre-slice resume snapshot, or a
fresher preempt payload when one landed), and the job re-enters the
queue after an exponential backoff. A job that exhausts its
:class:`RetryPolicy` lands in ``quarantined`` — terminal, with a
flight-recorder-style dump (fault history, tracebacks, last state
digest) attached to its status so the forensics survive the job.

All mutation happens on the scheduler thread; readers (``status()``, the
HTTP front-end) take the job lock only for the multi-field snapshots so a
mid-transition read never mixes two states' fields.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

JOB_QUEUED = "queued"
JOB_RUNNING = "running"
JOB_SUSPENDED = "suspended"
JOB_FAULTED = "faulted"
JOB_DONE = "done"
JOB_FAILED = "failed"
JOB_CANCELLED = "cancelled"
JOB_QUARANTINED = "quarantined"

_TERMINAL = (JOB_DONE, JOB_FAILED, JOB_CANCELLED, JOB_QUARANTINED)


class RetryPolicy:
    """Checkpointed-retry policy for faulted jobs: up to ``max_retries``
    requeues with exponential backoff (``backoff_s`` doubling by
    ``backoff_factor`` up to ``max_backoff_s``), optionally filtered to
    a set of fault classes (``retry_on`` — names from
    ``utils/faults.classify_fault``; None retries every class)."""

    def __init__(self, max_retries: int = 2, backoff_s: float = 0.25,
                 backoff_factor: float = 2.0, max_backoff_s: float = 30.0,
                 retry_on=None):
        self.max_retries = max(0, int(max_retries))
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff_s = float(max_backoff_s)
        self.retry_on = None if retry_on is None else frozenset(retry_on)

    def allows(self, fault_class: str, attempt: int) -> bool:
        """Whether retry number ``attempt`` (0-based) may run for a
        fault of this class."""
        if attempt >= self.max_retries:
            return False
        return self.retry_on is None or fault_class in self.retry_on

    def delay_s(self, attempt: int) -> float:
        return min(
            self.backoff_s * (self.backoff_factor ** attempt),
            self.max_backoff_s,
        )

    def to_dict(self) -> dict:
        return {
            "max_retries": self.max_retries,
            "backoff_s": self.backoff_s,
            "backoff_factor": self.backoff_factor,
            "max_backoff_s": self.max_backoff_s,
            "retry_on": (
                sorted(self.retry_on) if self.retry_on is not None else None
            ),
        }

    _FIELDS = ("max_retries", "backoff_s", "backoff_factor",
               "max_backoff_s", "retry_on")

    @classmethod
    def from_dict(cls, d: dict) -> "RetryPolicy":
        d = d or {}
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            # A typo'd key must be an error, not a silently-defaulted
            # policy the operator never asked for.
            raise ValueError(
                f"unknown retry-policy keys {sorted(unknown)} "
                f"(supported: {list(cls._FIELDS)})"
            )
        return cls(**d)


class CheckJob:
    """One submitted check: the model factory + builder options + spawn
    kwargs, the tenant's scheduling class (``priority`` high-first,
    ``deadline_s`` earliest-first within a priority, FIFO within a
    deadline), the per-tenant ``hbm_budget_mib``, the fault-tolerance
    envelope (``retry_policy``, ``timeout_s``), and the run state the
    scheduler threads through preempt/resume/retry cycles."""

    def __init__(
        self,
        job_id: str,
        model_factory: Callable,
        *,
        model_name: Optional[str] = None,
        options: Optional[dict] = None,
        spawn: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        hbm_budget_mib: Optional[float] = None,
        aot_namespace: Optional[str] = None,
        retry_policy: Optional[RetryPolicy] = None,
        timeout_s: Optional[float] = None,
        mode: str = "exhaustive",
        seed: int = 0,
        seq: int = 0,
        clock=time.monotonic,
    ):
        self.job_id = job_id
        self.run_id = job_id
        self.model_factory = model_factory
        self.model_name = model_name
        self.options = dict(options or {})
        self.spawn = dict(spawn or {})
        self.priority = int(priority)
        self.deadline_s = deadline_s
        self.tenant = tenant
        self.hbm_budget_mib = hbm_budget_mib
        self.aot_namespace = aot_namespace
        self.retry_policy = retry_policy
        self.timeout_s = timeout_s
        # Verification mode: "exhaustive" (device BFS over the full
        # space) or "swarm" (device-width randomized walks — state
        # spaces beyond the store; ``seed`` keys the reproducible walk
        # streams and rides the journal/status).
        self.mode = mode
        self.seed = int(seed)
        self.seq = seq
        self._clock = clock
        self._lock = threading.Lock()

        self.state = JOB_QUEUED
        self.payload: Optional[dict] = None  # suspended checkpoint
        self.result: Optional[dict] = None
        self.error: Optional[str] = None
        self.error_traceback: Optional[str] = None
        self.preempts = 0
        self.slices = 0
        # Fault-tolerance ledger: ``retries`` counts requeues after a
        # fault, ``faults`` is the per-fault history (class, error,
        # time), ``flight`` the forensic dump attached on
        # quarantine/failure, ``not_before`` the backoff gate a faulted
        # job waits behind, ``stall_preempts`` watchdog auto-preempts.
        self.retries = 0
        self.faults: list = []
        self.flight: Optional[dict] = None
        self.not_before: Optional[float] = None
        self.stall_preempts = 0
        # Durability (service_dir mode): True when the submission is
        # journalable (zoo name + JSON-safe spec) and would survive a
        # process crash via CheckService.recover().
        self.durable = False
        # Honest backend surfacing (the service fills these at admission
        # and corrects them from the live checker): ``preemptible`` —
        # the spawn method yields resumable preempt payloads (a False
        # here means this job SERIALIZES the device for its whole run);
        # ``packable`` — the job qualifies for tenant-packed waves
        # (``packable_reason`` says why not); ``packed`` — it actually
        # ran co-scheduled in at least one pack.
        self.preemptible: Optional[bool] = None
        self.packable = False
        self.packable_reason: Optional[str] = None
        self.packed = False
        # Liveness honesty (device-liveness PR): how this job's
        # `eventually` verdicts are produced ("device" / "host_pass" /
        # "default"), and — when the service downgraded the request —
        # the reason (e.g. a backend without device liveness).
        self.liveness_mode: Optional[str] = None
        self.liveness_reason: Optional[str] = None
        # Warm-start plane: ``warm_pool`` marks the service's internal
        # pre-compile jobs (excluded from SLO rows and the seed store);
        # ``warm_start`` means this run was seeded from a persisted
        # finished run (``seeded_from`` names the seed signature and
        # tier counts); ``warm_start_reason`` records why a seed was
        # NOT used (the honest conservative-fallback evidence).
        self.warm_pool = False
        self.warm_start = False
        self.seeded_from: Optional[dict] = None
        self.warm_start_reason: Optional[str] = None
        # Budget-derived device table sizing (None = service default).
        self.derived_table_capacity: Optional[int] = None
        # Pack-membership clock: join time of the current packed slice.
        self.pack_join_t: Optional[float] = None
        self.active_s = 0.0  # device-holding wall across slices
        self.warmup_s = 0.0  # summed compile warmup across incarnations
        self.submitted_t = clock()
        # Round-robin clock: a slice bumps it, so within one
        # (priority, deadline) class the scheduler always picks the
        # least-recently-run job — preempting a job only to re-pick it
        # would be pure checkpoint/restore churn.
        self.last_run_t = self.submitted_t
        self.started_t: Optional[float] = None
        self.first_discovery_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.seen_discoveries: set = set()
        self.cancel_event = threading.Event()
        self.done_event = threading.Event()

    # -- scheduler-side helpers --------------------------------------------

    def sort_key(self, last_run_override=None):
        """Admission order: priority high-first, then earliest absolute
        deadline, then round-robin (least recently run; FIFO among
        never-run jobs, whose clock is their submission time).
        ``last_run_override`` evaluates the key as if the job had just
        run — the quantum-expiry preemption test compares peers against
        the running job's REENTRY position with this, so the two always
        use one key shape."""
        deadline = (
            self.submitted_t + self.deadline_s
            if self.deadline_s is not None
            else float("inf")
        )
        last_run = (
            self.last_run_t if last_run_override is None else last_run_override
        )
        return (-self.priority, deadline, last_run, self.seq)

    def runnable(self) -> bool:
        if self.state in (JOB_QUEUED, JOB_SUSPENDED):
            return True
        if self.state == JOB_FAULTED:
            # Backoff gate: a faulted job re-enters the queue only once
            # its retry delay has elapsed.
            return (
                self.not_before is None
                or self._clock() >= self.not_before
            )
        return False

    def finish(self, state: str) -> None:
        with self._lock:
            self.state = state
            self.finished_t = self._clock()
        self.done_event.set()

    # State transitions take the job lock so a concurrent status() never
    # reads mixed fields (e.g. state "running" with a verdict attached).

    def suspend(self, payload: dict) -> None:
        with self._lock:
            self.payload = payload
            self.preempts += 1
            self.state = JOB_SUSPENDED

    def complete(self, result: dict) -> None:
        # Verdict and terminal state land under ONE lock acquisition —
        # a reader must never see state "running" with a result attached.
        with self._lock:
            self.result = result
            self.state = JOB_DONE
            self.finished_t = self._clock()
        self.done_event.set()

    def fail(self, error: str, traceback_text: Optional[str] = None,
             flight: Optional[dict] = None) -> None:
        with self._lock:
            self.error = error
            self.error_traceback = traceback_text
            if flight is not None:
                self.flight = flight
            self.payload = None
            self.state = JOB_FAILED
            self.finished_t = self._clock()
        self.done_event.set()

    def fault(self, fault_class: str, error: str,
              traceback_text: Optional[str] = None,
              payload: Optional[dict] = None,
              digest: Optional[dict] = None) -> str:
        """Routes one slice fault through the retry policy. Returns the
        resulting state: ``faulted`` (requeued after backoff, resuming
        from ``payload`` — the last good wave boundary the scheduler
        harvested), ``quarantined`` (retries exhausted; the flight dump
        lands on the job), or ``failed`` (no retry policy). The caller
        counts the metrics — this object only owns the transition."""
        now = self._clock()
        record = {
            "t": now,
            "class": fault_class,
            "error": error,
            "retry": self.retries,
        }
        with self._lock:
            self.faults.append(record)
            policy = self.retry_policy
            if policy is not None and policy.allows(
                fault_class, self.retries
            ):
                delay = policy.delay_s(self.retries)
                self.retries += 1
                self.payload = payload
                self.not_before = now + delay
                self.state = JOB_FAULTED
                return JOB_FAULTED
            # Terminal: quarantine when retries were exhausted (the
            # self-healing path gave up — keep the forensics), plain
            # failure when no retry was ever on the table.
            self.error = error
            self.error_traceback = traceback_text
            self.payload = None
            self.flight = {
                "error": error,
                "traceback": traceback_text,
                "fault_class": fault_class,
                "faults": list(self.faults),
                "retries": self.retries,
                "digest": digest,
            }
            if policy is not None and self.retries >= policy.max_retries:
                self.state = JOB_QUARANTINED
            else:
                self.state = JOB_FAILED
            self.finished_t = now
        self.done_event.set()
        return self.state

    # -- views --------------------------------------------------------------

    def latency(self) -> Dict[str, Optional[float]]:
        """The latency block every status/bench record carries:
        ``queued_s`` (submit -> first schedule), ``ttfv_s`` (submit ->
        first property discovery — time-to-first-violation for
        falsifiable workloads, time-to-first-witness for ``sometimes``),
        ``wall_s`` (submit -> terminal state, live runs: so far), and
        ``active_s`` (device-holding time across slices)."""
        now = self._clock()
        end = self.finished_t if self.finished_t is not None else now
        return {
            # A never-scheduled job's queue wait ends at its terminal
            # time (a cancelled-while-queued job must not report a
            # forever-growing queued_s).
            "queued_s": (self.started_t or end) - self.submitted_t,
            "ttfv_s": (
                self.first_discovery_t - self.submitted_t
                if self.first_discovery_t is not None
                else None
            ),
            "wall_s": end - self.submitted_t,
            "active_s": self.active_s,
        }

    def status(self) -> dict:
        with self._lock:
            out = {
                "job_id": self.job_id,
                "run_id": self.run_id,
                "model": self.model_name,
                "tenant": self.tenant,
                "priority": self.priority,
                "deadline_s": self.deadline_s,
                "hbm_budget_mib": self.hbm_budget_mib,
                "timeout_s": self.timeout_s,
                "mode": self.mode,
                "seed": self.seed,
                "state": self.state,
                "durable": self.durable,
                "preemptible": self.preemptible,
                "packable": self.packable,
                "packable_reason": self.packable_reason,
                "packed": self.packed,
                "liveness_mode": self.liveness_mode,
                "liveness_reason": self.liveness_reason,
                "warm_pool": self.warm_pool,
                "warm_start": self.warm_start,
                "seeded_from": self.seeded_from,
                "warm_start_reason": self.warm_start_reason,
                "preempts": self.preempts,
                "slices": self.slices,
                "retries": self.retries,
                "faults": [dict(f) for f in self.faults],
                "stall_preempts": self.stall_preempts,
                "discoveries_so_far": sorted(self.seen_discoveries),
                "latency": self.latency(),
                "result": self.result,
                "error": self.error,
                "error_traceback": self.error_traceback,
                "flight": self.flight,
            }
        return out

    # The scalar result fields the job-list view keeps; the heavy ones
    # (golden report text, attribution/coverage ledgers, per-discovery
    # detail) stay on the single-job view.
    _SUMMARY_RESULT_FIELDS = (
        "unique", "states", "max_depth", "properties_hold", "rate",
    )

    def summary(self) -> dict:
        """``status()`` minus the heavy result payload — what the
        ``GET /jobs`` listing (polled every ~2s by the UI panel)
        actually renders. Full verdicts (and the flight dump /
        traceback forensics) stay on ``GET /jobs/<id>``."""
        out = self.status()
        result = out.get("result")
        if isinstance(result, dict):
            out["result"] = {
                k: result.get(k) for k in self._SUMMARY_RESULT_FIELDS
            }
        out.pop("flight", None)
        out.pop("error_traceback", None)
        return out


class JobHandle:
    """The caller's view of a submitted job (the Python-API surface the
    HTTP front-end mirrors)."""

    def __init__(self, job: CheckJob, service):
        self._job = job
        self._service = service

    @property
    def job_id(self) -> str:
        return self._job.job_id

    def done(self) -> bool:
        return self._job.done_event.is_set()

    def status(self) -> dict:
        return self._job.status()

    def cancel(self) -> bool:
        """Requests cancellation; True unless the job already reached a
        terminal state. A running job is preempted at its next wave
        boundary and its payload discarded."""
        if self._job.state in _TERMINAL:
            return False
        self._job.cancel_event.set()
        self._service._wake()
        return True

    def result(self, timeout: Optional[float] = None) -> dict:
        """Blocks for the verdict. Raises ``TimeoutError`` on timeout,
        ``RuntimeError`` for a failed, quarantined, or cancelled job."""
        if not self._job.done_event.wait(timeout):
            raise TimeoutError(
                f"job {self._job.job_id} not done within {timeout}s"
            )
        if self._job.state in (JOB_FAILED, JOB_QUARANTINED):
            raise RuntimeError(
                f"job {self._job.job_id} {self._job.state}: "
                f"{self._job.error}"
            )
        if self._job.state == JOB_CANCELLED:
            raise RuntimeError(f"job {self._job.job_id} was cancelled")
        return self._job.result
