"""The persistent check scheduler: one device, many jobs, packed waves.

``CheckService`` owns the accelerator the way a database owns its disk: a
scheduler thread admits :class:`CheckJob` s (priority high-first, EDF
within a priority, FIFO within a deadline) and multiplexes the device two
ways:

- **Tenant-packed waves (the default for qualifying jobs).** Same-shape
  jobs — same zoo configuration, no spawn overrides, no symmetry/target
  caps/budget — co-schedule onto ONE physical wave through
  ``checker/packed_tenancy.TenantPackedEngine``: a shared visited table
  under tenant-salted fingerprints, per-lane tenant ids, per-tenant
  result reductions. Concurrency costs ~nothing (BENCH_r12 vs the
  BENCH_r10 time-sliced baseline), admission is "claim a free lane
  slot", late arrivals JOIN the live pack mid-run, and preemption is
  "drop the tenant's lanes" — its survivors hand back as a checkpoint-v2
  payload slice with no device drain. Every packed tenant's verdict is
  bit-identical to its solo run (tests/test_packed_tenancy.py).
- **Wave-granular time-slicing (the fallback).** Non-packable jobs are
  suspended by ``request_preempt()`` (wave state drains to a host-side
  checkpoint payload at the next wave/drain boundary) and resumed later
  with ``resume_from=<payload>`` — bit-identical to an uninterrupted run
  (tests/test_preempt_resume.py). Jobs whose backend cannot preempt at
  all run their slice to completion; that fact is surfaced honestly as
  ``preemptible: false`` in ``status()`` instead of being discovered
  from a swallowed NotImplementedError.

Jobs multiplex onto the shared AOT rung cache (``checker/tpu.py``'s
``shared_aot_cache``): two jobs of the same zoo configuration share every
``(bucket, table_capacity)`` wave/drain executable (the packed engine
shares its wave/seed/rehash executables the same way), so the second job
— and every preempted job's next incarnation — records zero compile
phases. Each job runs under its own ``run_id``: its own metrics registry
and run-stamped trace spans, so per-job ``/metrics`` / ``/status`` / SSE
/ attribution / coverage all work, and packed jobs additionally carry
their ``pack.tenant.*`` lane accounting (PR 3-8 + PR 12 plumbing).

Single-device by design: slices (packed or solo) are strictly
serialized, so the device never has two claimants (the same constraint
the bench's sentinel coordination enforces across processes, here
enforced by the scheduler loop within one).
"""

from __future__ import annotations

import io
import itertools
import json
import os
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from ..report import WriteReporter
from ..utils.faults import classify_fault, tenant_fault_of
from .jobs import (
    JOB_CANCELLED,
    JOB_DONE,
    JOB_FAILED,
    JOB_FAULTED,
    JOB_QUARANTINED,
    JOB_QUEUED,
    JOB_RUNNING,
    JOB_SUSPENDED,
    CheckJob,
    JobHandle,
    RetryPolicy,
)
from .zoo import aot_namespace as zoo_namespace
from .zoo import default_zoo


class QueueFullError(RuntimeError):
    """Admission rejected: the service's bounded queue is full. The
    HTTP front-end maps this to 429 with a Retry-After hint."""

    def __init__(self, limit: int, retry_after_s: float):
        super().__init__(
            f"admission queue full ({limit} jobs pending); retry in "
            f"~{retry_after_s:.1f}s"
        )
        self.retry_after_s = retry_after_s


def _format_exc(exc: BaseException) -> str:
    """The full formatted traceback chain for one exception — what
    status()['error_traceback'] and the flight dumps carry (repr(e)
    alone loses the stack, which is the whole point of the dump)."""
    return "".join(
        traceback.format_exception(type(exc), exc, exc.__traceback__)
    )

# Builder options POST /jobs and submit(options=...) accept.
_BUILDER_OPTIONS = ("target_state_count", "target_max_depth", "symmetry")

# Spawn kwargs the service defaults for every job: a bounded drain cap is
# what makes preemption latency a few waves instead of a whole drain (the
# same clamp checkpoint durability applies), and modest capacities fit
# many tenants on one device.
_DEFAULT_SPAWN = {
    "frontier_capacity": 1 << 10,
    "table_capacity": 1 << 16,
    "max_drain_waves": 8,
}

# Spawn kwargs for mode="swarm" jobs (checker/swarm.py): one fleet shape
# for every swarm job on the service, which is what lets them pack into
# one stacked dispatch — and what makes a packed tenant's walks
# bit-identical to the same job run solo.
_DEFAULT_SWARM_SPAWN = {
    "lanes": 512,
    "wave_steps": 256,
    "max_trace_len": 128,
    "sample_capacity": 1 << 14,
    "sample_stride": 1,
}

_JOB_MODES = ("exhaustive", "swarm", "conformance")

# mode="conformance" spawn surface (conformance/checker.py knobs); any
# other key is a known-at-admission error, not a mid-run TypeError.
_CONFORMANCE_SPAWN_KEYS = frozenset({"batch_lanes", "parity"})

# The warm pool's conformance geometry: the replay executable compiled
# per warm shape (padded trace length x lane count) — matches the
# checker's default batch_lanes and the smallest trace bucket.
_CONFORMANCE_WARM_T = 16
_CONFORMANCE_WARM_L = 64


def _normalize_conformance(payload):
    """A conformance submission payload -> ``(canonical wire lines,
    decoded records)``, strictly validated (the first bad frame raises
    ``WireRefusal``, a ``ValueError`` — HTTP 400 at the door). Accepts
    JSONL text, a list of frame lines, or a list of frame objects (raw
    frames with ``"v"``, or already-decoded records)."""
    import json as _json

    from ..conformance.wire import decode_lines, encode_record

    if isinstance(payload, str):
        lines = payload.splitlines()
    elif isinstance(payload, (list, tuple)):
        lines = []
        for item in payload:
            if isinstance(item, str):
                lines.append(item)
            elif isinstance(item, dict):
                try:
                    lines.append(encode_record(item))
                except (KeyError, TypeError, ValueError):
                    # Not frame-shaped at all: serialize as-is and let
                    # the strict decode refuse it with a line number.
                    lines.append(_json.dumps(item))
            else:
                raise ValueError(
                    "conformance frames must be JSONL lines or frame "
                    f"objects, got {type(item).__name__}"
                )
    else:
        raise ValueError(
            "conformance payload must be JSONL text, a list of frame "
            f"lines, or a list of frame objects, got "
            f"{type(payload).__name__}"
        )
    lines = [ln for ln in (s.strip() for s in lines) if ln]
    if not lines:
        raise ValueError("conformance payload is empty")
    records, _refusals = decode_lines(lines, strict=True)
    return lines, records

# Default job ids are unique across every service in the process (the
# id is also the run_id, which keys process-global registries).
_GLOBAL_JOB_SEQ = itertools.count()

# Spawn methods whose checkers yield resumable preempt payloads
# (``Checker.supports_preempt``). The admission-time guess; corrected
# from the live checker after the first spawn.
_PREEMPTIBLE_SPAWNS = frozenset({"spawn_tpu_bfs", "spawn_sharded_tpu_bfs"})

# Spawn methods whose checkers honor liveness="device"
# (``Checker.supports_device_liveness``) — the admission-time guess for
# the honest liveness_mode/downgrade-reason surface, corrected from the
# live checker after the first spawn.
_DEVICE_LIVENESS_SPAWNS = frozenset(
    {"spawn_tpu_bfs", "spawn_sharded_tpu_bfs"}
)


class CheckService:
    """A long-lived, in-process checking service.

    ::

        svc = CheckService()
        h1 = svc.submit(model_name="2pc", model_args={"rm_count": 5})
        h2 = svc.submit(model_name="abd", priority=1)   # runs first
        print(h1.result()["unique"], h1.status()["latency"]["ttfv_s"])
        svc.close()

    ``quantum_s`` is the scheduling quantum: a running job is preempted
    once its slice exceeds it *and* another job is runnable (a sole job
    runs uninterrupted — preemption exists for sharing, not ceremony).
    ``default_hbm_budget_mib`` is the per-tenant device budget applied to
    jobs that don't set their own (the PR 5 tiered store enforces it).
    """

    def __init__(
        self,
        *,
        quantum_s: float = 1.0,
        poll_interval_s: float = 0.005,
        zoo: Optional[Dict[str, Callable]] = None,
        default_spawn: Optional[dict] = None,
        default_hbm_budget_mib: Optional[float] = None,
        spawn_method: str = "spawn_tpu_bfs",
        default_swarm_spawn: Optional[dict] = None,
        max_finished_jobs: int = 256,
        packing: bool = True,
        max_pack_tenants: int = 8,
        pack_async: bool = False,
        retry_policy: Optional[RetryPolicy] = "default",
        max_queued_jobs: Optional[int] = None,
        service_dir: Optional[str] = None,
        stall_deadline_s: Optional[float] = None,
        on_stall: Optional[Callable] = None,
        slo_targets: Optional[dict] = None,
        max_run_registries: int = 64,
        warm_pool=None,
        warm_start: bool = True,
        clock=time.monotonic,
    ):
        self.quantum_s = float(quantum_s)
        self.poll_interval_s = float(poll_interval_s)
        self.zoo = dict(zoo) if zoo is not None else default_zoo()
        self.default_spawn = dict(_DEFAULT_SPAWN)
        if default_spawn:
            self.default_spawn.update(default_spawn)
        self.default_hbm_budget_mib = default_hbm_budget_mib
        self.spawn_method = spawn_method
        # mode="swarm" fleet shape (see _DEFAULT_SWARM_SPAWN).
        self.default_swarm_spawn = dict(_DEFAULT_SWARM_SPAWN)
        if default_swarm_spawn:
            self.default_swarm_spawn.update(default_swarm_spawn)
        # Tenant-packed waves (checker/packed_tenancy.py): qualifying
        # same-shape jobs share one physical dispatch instead of
        # time-slicing. ``packing=False`` restores the pure time-slicer;
        # ``max_pack_tenants`` is the lane-slot count K;
        # ``pack_async=True`` runs the pack's host half (per-tenant
        # probes, parent logs, survivor re-entry) on a pipeline worker
        # overlapped with the next dispatch.
        self.packing = bool(packing)
        self.max_pack_tenants = max(1, int(max_pack_tenants))
        self.pack_async = bool(pack_async)
        # Zoo-configuration model cache: one model instance per AOT
        # namespace, shared by admission-time budget validation and the
        # packed engines (models are pure packed-array containers).
        self._pack_models: Dict[str, object] = {}
        # Retention: terminal jobs (and their run registries) beyond
        # this count are evicted oldest-first, so a long-lived service
        # does not accrete one registry + result blob per finished job
        # forever. Live JobHandles keep working — they hold the job
        # object, not the index entry.
        self.max_finished_jobs = max(0, int(max_finished_jobs))
        # Fault tolerance (the self-healing layer): the default retry
        # policy applied to jobs that don't bring their own — pass
        # retry_policy=None to restore fail-on-first-fault.
        self.retry_policy = (
            RetryPolicy() if retry_policy == "default" else retry_policy
        )
        # Graceful degradation: ``max_queued_jobs`` bounds the pending
        # backlog (submit raises QueueFullError / HTTP 429 past it);
        # ``stall_deadline_s`` arms a per-slice stall watchdog whose
        # action hook (``on_stall(job, checker, idle_s)``; default:
        # auto-preempt so the job retries from its wave boundary) fires
        # when a slice makes no progress for that long.
        self.max_queued_jobs = (
            None if max_queued_jobs is None else max(1, int(max_queued_jobs))
        )
        self.stall_deadline_s = stall_deadline_s
        self.on_stall = on_stall
        # Durable recovery: ``service_dir`` adds a write-ahead JSONL job
        # journal plus atomic per-job checkpoint pickles, so
        # ``CheckService.recover(service_dir)`` rebuilds the queue after
        # a process crash (README "Fault tolerance & recovery").
        self.service_dir = service_dir
        self._journal_lock = threading.Lock()
        self._journal_fh = None
        if service_dir is not None:
            os.makedirs(os.path.join(service_dir, "jobs"), exist_ok=True)
            self._journal_fh = open(
                os.path.join(service_dir, "journal.jsonl"), "a",
                encoding="utf-8",
            )
        # Warm-start plane (README "Warm-start serving"): with a
        # service_dir, compiled executables persist under ``aot/``
        # (fenced, content-addressed — checkers probe it on in-memory
        # AOT misses) and finished exhaustive runs seed ``seeds/`` so a
        # resubmitted model completes in O(verify). ``warm_start=False``
        # keeps the directories untouched (cold semantics, e.g. for
        # benchmark reference legs).
        self.warm_start = bool(warm_start)
        self.aot_store = None
        self.seed_store = None
        if service_dir is not None and self.warm_start:
            from ..storage.persist import AotDiskStore, SeedStore

            self.aot_store = AotDiskStore(os.path.join(service_dir, "aot"))
            self.seed_store = SeedStore(os.path.join(service_dir, "seeds"))
        # Conformance corpus persistence: named JSONL uploads under
        # ``corpus/`` so HTTP clients can submit by NAME (never by
        # server-side path — see service/http.py's spawn-key security
        # note) and re-audit a stored corpus after restarts.
        self.corpus_store = None
        if service_dir is not None:
            from ..storage.corpus import CorpusStore

            self.corpus_store = CorpusStore(
                os.path.join(service_dir, "corpus")
            )
        from ..telemetry import metrics_registry

        reg = metrics_registry()
        self._m_faults = reg.counter("fault.jobs")
        self._m_retries = reg.counter("retry.scheduled")
        self._m_recovered = reg.counter("retry.recovered")
        self._m_quarantined = reg.counter("retry.quarantined")
        self._m_stall_preempts = reg.counter("service.stall.auto_preempts")
        self._m_rejected = reg.counter("service.admission.rejected")
        self._m_timeouts = reg.counter("service.timeouts")
        self._m_close_stuck = reg.counter("service.close.stuck")
        self._m_ckpt_errors = reg.counter(
            "service.recovery.checkpoint_errors"
        )
        self._fault_class_counter = (
            lambda cls: reg.counter(f"fault.by_class.{cls}")
        )
        # SLO ledger (service/slo.py): per-mode ttfv/verdict percentiles
        # + queue/compile/explore decomposition, fed at the two verdict
        # sites; ``slo_targets`` arms the burn-rate gauges.
        from .slo import SLOLedger

        self.slo = SLOLedger(targets=slo_targets, registry=reg)
        # Registry retention, tighter than job retention: a RETAINED
        # terminal job's run registry (hundreds of instruments) costs
        # far more than its summary row, so registries beyond this cap
        # are dropped oldest-first while the job records stay (their
        # /jobs views keep working — results are snapshotted on the job).
        self.max_run_registries = max(0, int(max_run_registries))
        self._m_registry_evicted = reg.counter("service.registry_evicted")
        # Warm-start observability (global registry: plane-level, not
        # per-run). Per-job aot_cache.* counters live in run registries.
        self._m_seed_saved = reg.counter("warmstart.seed_saved")
        self._m_seed_loaded = reg.counter("warmstart.seed_loaded")
        self._m_seed_refused = reg.counter("warmstart.seed_refused")
        self._g_pool_ready = reg.gauge("warmstart.pool_ready")
        self._g_pool_pending = reg.gauge("warmstart.pool_pending")
        self._clock = clock
        self._admission_hold = False  # recover() gates scheduling
        self._cond = threading.Condition()
        self._jobs: Dict[str, CheckJob] = {}
        self._seq = itertools.count()
        self._closing = threading.Event()
        self._active_checker = None
        self._scheduler = threading.Thread(
            target=self._run_scheduler, name="check-service", daemon=True
        )
        self._scheduler.start()
        # Warm pool: pre-compile registered shapes on a background
        # thread at service start so a fresh process serves its first
        # real job compile-free. ``warm_pool=True`` warms the zoo's
        # registered shapes; an iterable of ``(model_name, model_args)``
        # pairs warms exactly those. Warm jobs ride the normal scheduler
        # at rock-bottom priority (they never starve real work — the
        # admission order is priority-high-first) and are excluded from
        # the SLO ledger and the seed store.
        self.warm_pool_status: Dict[str, dict] = {}
        self._warm_pool_thread = None
        if warm_pool:
            shapes = self._warm_shapes(warm_pool)
            for ns, name, args in shapes:
                self.warm_pool_status[ns] = {
                    "model": name, "args": dict(args), "state": "pending",
                }
            self._g_pool_pending.set(len(shapes))
            self._warm_pool_thread = threading.Thread(
                target=self._warm_pool_worker, args=(shapes,),
                name="check-service-warm-pool", daemon=True,
            )
            self._warm_pool_thread.start()

    # -- submission ---------------------------------------------------------

    def submit(
        self,
        model=None,
        *,
        model_name: Optional[str] = None,
        model_args: Optional[dict] = None,
        options: Optional[dict] = None,
        spawn: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        hbm_budget_mib: Optional[float] = None,
        aot_namespace: Optional[str] = None,
        job_id: Optional[str] = None,
        timeout_s: Optional[float] = None,
        retry_policy: Optional[RetryPolicy] = "default",
        mode: str = "exhaustive",
        seed: int = 0,
        conformance=None,
        _warm_pool: bool = False,
    ) -> JobHandle:
        """Admits one check job; returns immediately with a handle.

        Either ``model_name`` (a zoo entry; ``model_args`` forwarded to
        its factory — this route shares the AOT cache automatically) or
        ``model`` (a ``BatchableModel`` instance or zero-arg factory;
        pass ``aot_namespace=`` yourself iff submissions under that
        namespace are configured identically). ``options`` takes the
        builder knobs (``target_state_count``, ``target_max_depth``,
        ``symmetry``); ``spawn`` any ``spawn_tpu_bfs`` kwarg;
        ``hbm_budget_mib`` the tenant's device budget. ``mode="swarm"``
        runs device-width randomized walks instead of exhaustive BFS
        (state spaces beyond the store; ``seed`` keys the reproducible
        walk streams — same seed, same verdict, packed or solo)."""
        if self._closing.is_set():
            raise RuntimeError("CheckService is closed")
        if conformance is not None and mode == "exhaustive":
            mode = "conformance"
        if mode not in _JOB_MODES:
            raise ValueError(
                f"unknown mode {mode!r} (supported: {list(_JOB_MODES)})"
            )
        conformance_lines = conformance_records = None
        if mode == "conformance":
            # Conformance jobs audit recorded executions, not a model:
            # the payload is wire frames (see conformance/wire.py), the
            # only tuning surface is the batch geometry, and every other
            # check-job knob that presupposes exploration is a
            # known-at-admission error.
            if conformance is None:
                raise ValueError(
                    "mode='conformance' needs conformance= (wire frames: "
                    "JSONL text, a list of frame lines, or a list of "
                    "frame objects)"
                )
            if model is not None or model_name is not None:
                raise ValueError(
                    "conformance jobs audit recorded frames; trace "
                    "frames name their zoo model inline — do not pass "
                    "model/model_name"
                )
            if options:
                raise ValueError(
                    "conformance jobs take no builder options; tune "
                    f"spawn={sorted(_CONFORMANCE_SPAWN_KEYS)} instead"
                )
            if hbm_budget_mib is not None:
                raise ValueError(
                    "conformance jobs have no tiered visited store to "
                    "budget; size batches via spawn={'batch_lanes': ...}"
                )
            bad_spawn = set(spawn or {}) - _CONFORMANCE_SPAWN_KEYS
            if bad_spawn:
                raise ValueError(
                    f"unknown conformance spawn keys {sorted(bad_spawn)} "
                    f"(supported: {sorted(_CONFORMANCE_SPAWN_KEYS)})"
                )
            # Strict decode at admission: a malformed frame is a 400 at
            # the door (WireRefusal is a ValueError), not a burned retry
            # mid-run. The canonical re-encoded lines are what the
            # durable journal carries.
            conformance_lines, conformance_records = (
                _normalize_conformance(conformance)
            )
        try:
            seed = int(seed)
        except (TypeError, ValueError):
            raise ValueError("seed must be an integer") from None
        if mode == "swarm" and hbm_budget_mib is not None:
            raise ValueError(
                "mode='swarm' has no tiered visited store to budget; "
                "size the walk sample via default_swarm_spawn/"
                "spawn={'sample_capacity': ...} instead"
            )
        if mode == "swarm" and (options or {}).get("symmetry"):
            # Known-at-admission conflict: SwarmChecker refuses
            # symmetry at spawn (cycle checks are host-only) — reject
            # HERE, not as a mid-run failure burning retries.
            raise ValueError(
                "mode='swarm' does not support symmetry reduction "
                "(walk cycle detection is host-only; use "
                "spawn_simulation for symmetric models)"
            )
        if mode == "swarm":
            # The walk carry holds targets as int32 runtime scalars —
            # an out-of-range value is a known-at-admission config
            # error (mid-run it would burn the retry budget on the
            # packed path), same convention as the checks above.
            for knob in ("target_state_count", "target_max_depth"):
                v = (options or {}).get(knob)
                if v is None:
                    continue
                try:
                    v = int(v)
                except (TypeError, ValueError):
                    raise ValueError(
                        f"{knob} must be an integer"
                    ) from None
                if not 0 < v < 2**31:
                    raise ValueError(
                        f"{knob}={v} is outside the int32 range the "
                        "walk carry uses; split the budget across "
                        "resumed runs"
                    )
        if mode == "swarm" and not (
            (options or {}).get("target_state_count")
            or timeout_s is not None
        ):
            # Another known-at-admission conflict: simulation semantics
            # only stop when EVERY property has a discovery, so a model
            # with a holding always-property samples forever — a job
            # with no stop bound would occupy the device indefinitely,
            # suspended and resumed every quantum.
            raise ValueError(
                "mode='swarm' needs a stop bound (a holding property "
                "is never 'discovered', so an unbounded walk samples "
                "forever): pass options={'target_state_count': N} "
                "and/or timeout_s"
            )
        for field_name, value in (
            ("model_args", model_args),
            ("options", options),
            ("spawn", spawn),
        ):
            if value is not None and not isinstance(value, dict):
                raise ValueError(
                    f"{field_name} must be an object/dict, "
                    f"got {type(value).__name__}"
                )
        model_args = dict(model_args or {})
        if model_name is not None:
            if model is not None:
                raise ValueError("pass model or model_name, not both")
            try:
                factory_fn = self.zoo[model_name]
            except KeyError:
                raise ValueError(
                    f"unknown model {model_name!r} "
                    f"(zoo has: {sorted(self.zoo)})"
                ) from None
            def factory(fn=factory_fn, kw=model_args):
                return fn(**kw)
            if aot_namespace is None:
                # Canonicalize zoo aliases ("2pc"/"two_phase_commit" map
                # to one factory): namespace on the factory's first zoo
                # name, so aliases share the executable cache instead of
                # recompiling per spelling.
                canonical = min(
                    k for k, v in self.zoo.items() if v is factory_fn
                )
                aot_namespace = zoo_namespace(canonical, model_args)
        elif model is not None:
            if callable(model) and not hasattr(model, "packed_init_states"):
                factory = model
            else:
                def factory(m=model):
                    return m
        elif mode == "conformance":
            # No model to build: trace frames resolve their zoo entry
            # inside the checker, histories need none at all.
            def factory():
                return None
        else:
            raise ValueError("one of model / model_name is required")
        bad = set(options or {}) - set(_BUILDER_OPTIONS)
        if bad:
            raise ValueError(
                f"unknown options {sorted(bad)} "
                f"(supported: {list(_BUILDER_OPTIONS)})"
            )
        # Coerce the scheduling inputs HERE, not in the scheduler: a
        # non-numeric deadline from an HTTP body reaching sort_key()
        # would kill the scheduler thread and hang every job.
        try:
            priority = int(priority)
            deadline_s = None if deadline_s is None else float(deadline_s)
            hbm_budget_mib = (
                None if hbm_budget_mib is None else float(hbm_budget_mib)
            )
            timeout_s = None if timeout_s is None else float(timeout_s)
        except (TypeError, ValueError) as e:
            raise ValueError(
                "priority must be an int; deadline_s / hbm_budget_mib / "
                f"timeout_s must be numbers or null ({e})"
            ) from None
        if retry_policy == "default":
            retry_policy = self.retry_policy
        if retry_policy is not None and not isinstance(
            retry_policy, RetryPolicy
        ):
            if isinstance(retry_policy, dict):
                try:
                    retry_policy = RetryPolicy.from_dict(retry_policy)
                except (TypeError, ValueError) as e:
                    raise ValueError(f"bad retry policy: {e}") from None
            else:
                raise ValueError(
                    "retry_policy must be a RetryPolicy, a dict of its "
                    "fields, or None"
                )
        if hbm_budget_mib is None and mode not in ("swarm", "conformance"):
            # The service-wide default budget never applies to swarm or
            # conformance jobs — their device footprint is a fixed lane
            # shape, not a growing visited table.
            hbm_budget_mib = self.default_hbm_budget_mib
        # Budget-derived table sizing, validated AT ADMISSION: an
        # over-budget request (the budget cannot fit even one worst-case
        # wave of this model at the configured frontier) is rejected
        # here with a clear error, not discovered as an OOM/ValueError
        # on the scheduler thread mid-slice.
        derived_table_capacity = None
        if hbm_budget_mib is not None:
            derived_table_capacity = self._validate_budget(
                factory, aot_namespace, spawn, hbm_budget_mib
            )
        if mode == "conformance":
            packable, packable_reason = False, (
                "conformance batches are internally lane-packed (lanes "
                "= traces/histories); cross-tenant packing would break "
                "per-upload verdict determinism"
            )
        elif mode == "swarm":
            packable, packable_reason = self._classify_packable_swarm(
                aot_namespace=aot_namespace, options=options, spawn=spawn
            )
        else:
            packable, packable_reason = self._classify_packable(
                aot_namespace=aot_namespace,
                options=options,
                spawn=spawn,
                hbm_budget_mib=hbm_budget_mib,
            )
        if (
            packable
            and not _warm_pool
            and self.seed_store is not None
            and mode == "exhaustive"
            and self.spawn_method == "spawn_tpu_bfs"
            and not (options or {}).get("target_state_count")
            and not (options or {}).get("target_max_depth")
            and not (options or {}).get("complete_liveness")
        ):
            # Warm-start plane: seed artifacts ride the SOLO checkpoint
            # format (one visited-tier payload, empty frontier) — the
            # packed engine's per-tenant lanes cannot restore a
            # storage-seeded L1. A seed-eligible job therefore runs
            # solo; the reason is surfaced, not silent.
            packable = False
            packable_reason = (
                "warm-start plane: runs solo (seeds ride the solo "
                "checkpoint format)"
            )
        with self._cond:
            if self.max_queued_jobs is not None:
                # Bounded admission: graceful 429-style degradation
                # beats an unbounded backlog silently growing past any
                # deadline the tenants could still meet.
                backlog = sum(
                    1
                    for j in self._jobs.values()
                    if j.state
                    in (JOB_QUEUED, JOB_SUSPENDED, JOB_FAULTED, JOB_RUNNING)
                )
                if backlog >= self.max_queued_jobs:
                    self._m_rejected.inc()
                    raise QueueFullError(
                        self.max_queued_jobs,
                        retry_after_s=max(self.quantum_s, 1.0),
                    )
            seq = next(self._seq)
            # Default ids draw from the PROCESS-global sequence, not the
            # per-service one: the id doubles as the run_id keying the
            # process-global metrics registries, so two services in one
            # process (common in tests, possible in embedders) must
            # never mint the same "job-0" and merge two jobs' counters.
            jid = job_id or f"job-{next(_GLOBAL_JOB_SEQ)}"
            if jid in self._jobs:
                raise ValueError(f"duplicate job_id {jid!r}")
            job = CheckJob(
                jid,
                factory,
                model_name=model_name,
                options=options,
                spawn=spawn,
                priority=priority,
                deadline_s=deadline_s,
                tenant=tenant,
                hbm_budget_mib=hbm_budget_mib,
                aot_namespace=aot_namespace,
                retry_policy=retry_policy,
                timeout_s=timeout_s,
                mode=mode,
                seed=seed,
                seq=seq,
                clock=self._clock,
            )
            job.preemptible = (
                True
                # SwarmChecker / ConformanceChecker .supports_preempt
                if mode in ("swarm", "conformance")
                else self.spawn_method in _PREEMPTIBLE_SPAWNS
            )
            job.packable = packable
            job.packable_reason = packable_reason
            job.liveness_mode, job.liveness_reason = (
                self._classify_liveness(options, spawn, mode=mode)
            )
            job.derived_table_capacity = derived_table_capacity
            if _warm_pool:
                # Internal pre-compile job from the warm pool: never
                # packed (packing would skip the solo executables real
                # jobs need), never SLO-observed, never seeded.
                job.warm_pool = True
                job.packable = False
                job.packable_reason = "warm-pool precompile job"
            # The zoo kwargs, kept for the durable journal's
            # resubmission spec (the factory closure hides them).
            job._journal_model_args = (
                dict(model_args) if model_name is not None else None
            )
            if mode == "conformance":
                # Canonical wire lines for the journal; decoded records
                # for the checker (decoding is deterministic, so both
                # incarnations see identical inputs).
                job._conformance_lines = conformance_lines
                job._conformance_records = conformance_records
            self._jobs[jid] = job
            self._cond.notify_all()
        self._journal_submit(job)
        return JobHandle(job, self)

    # -- admission policy ---------------------------------------------------

    # Model-cache cap: a long-lived service fed many distinct zoo
    # configurations must not pin a packed-array model instance per
    # namespace forever (same retention rule as max_finished_jobs).
    _PACK_MODEL_CACHE_MAX = 32

    def _model_for(self, factory: Callable, aot_namespace: Optional[str]):
        """The job's model instance — cached per AOT namespace (the
        namespace asserts identical configuration, so one instance
        serves budget validation and every pack under that key);
        oldest-inserted entries evict past the cap."""
        if aot_namespace is None:
            return factory()
        model = self._pack_models.get(aot_namespace)
        if model is None:
            model = factory()
            self._pack_models[aot_namespace] = model
            while len(self._pack_models) > self._PACK_MODEL_CACHE_MAX:
                self._pack_models.pop(next(iter(self._pack_models)))
        return model

    def _validate_budget(
        self, factory, aot_namespace, spawn, hbm_budget_mib
    ) -> int:
        """Derives the tenant's device table capacity from its
        ``hbm_budget_mib`` (the budget IS the tenant's paid allocation —
        the fixed ``_DEFAULT_SPAWN`` constant both over-allocated poor
        tenants and growth-churned rich ones) and rejects inadmissible
        budgets up front. Returns the capacity in rows."""
        from ..checker.tpu import min_admissible_hbm_budget_mib
        from ..storage import max_table_rows_for_budget

        frontier = (spawn or {}).get(
            "frontier_capacity",
            self.default_spawn.get("frontier_capacity", 1 << 10),
        )
        model = self._model_for(factory, aot_namespace)
        min_budget = min_admissible_hbm_budget_mib(model, frontier)
        if hbm_budget_mib < min_budget:
            raise ValueError(
                f"hbm_budget_mib={hbm_budget_mib} rejected at admission: "
                f"one worst-case wave at frontier_capacity={frontier} "
                f"needs at least {min_budget:.3f} MiB for this model; "
                "raise the budget or shrink frontier_capacity"
            )
        return max_table_rows_for_budget(hbm_budget_mib)

    # default_spawn keys the packed engine either honors directly
    # (frontier/table shape, async pipelining) or that cannot change
    # packed semantics (max_drain_waves bounds SOLO preemption latency —
    # the engine is wave-granular by construction; aot_cache names the
    # SOLO executable namespace — packs use their own "pack:" one). Any
    # other service-wide default (budgets, expand_fps, hashset_impl,
    # checkpointing, ...) would be silently dropped by packing, so its
    # presence honestly disqualifies packing instead.
    _PACK_SAFE_DEFAULT_SPAWN = frozenset({
        "frontier_capacity",
        "table_capacity",
        "max_drain_waves",
        "aot_cache",
        "async_pipeline",
        # The packed engine honors device liveness directly (per-tenant
        # edge partitions; checker/packed_tenancy.py).
        "liveness",
    })

    def _classify_liveness(self, options, spawn, mode="exhaustive"):
        """The job's ``eventually``-verdict mode and, when the service
        must downgrade the request (backend without device liveness),
        the honest reason — the PR 12 ``packable_reason`` pattern, so
        unsound-by-default semantics are visible in ``status()`` rather
        than discovered from a missed counterexample."""
        if mode == "conformance":
            # Verdicts are per-record replay/audit, not temporal
            # properties — there is nothing for a liveness mode to mean.
            return "default", None
        requested = (spawn or {}).get(
            "liveness", self.default_spawn.get("liveness")
        )
        host_pass = bool((options or {}).get("complete_liveness"))
        if mode == "swarm":
            if requested == "device" or host_pass:
                return "default", (
                    "swarm walks are sampling-based: eventually "
                    "verdicts come from walk-local traces (no edge "
                    "store, no lasso pass) — absence is never certified"
                )
            return "default", None
        if requested == "device":
            if self.spawn_method in _DEVICE_LIVENESS_SPAWNS:
                return "device", None
            reason = (
                f"backend {self.spawn_method!r} has no device liveness; "
                + (
                    "downgraded to the host post-pass"
                    if host_pass
                    else "downgraded to default (reference-parity) "
                    "semantics — eventually verdicts keep the "
                    "documented false negatives"
                )
            )
            return ("host_pass" if host_pass else "default"), reason
        if host_pass:
            return "host_pass", None
        return "default", None

    def _classify_packable(self, *, aot_namespace, options, spawn,
                           hbm_budget_mib):
        """Whether a submission qualifies for tenant-packed waves, and
        the honest reason when it does not (surfaced via ``status()`` so
        operators can see which jobs serialize the device)."""
        if not self.packing:
            return False, "packing disabled on this service"
        if self.spawn_method != "spawn_tpu_bfs":
            return False, f"spawn_method {self.spawn_method!r}"
        if aot_namespace is None:
            return False, "custom model (no AOT namespace to pack under)"
        if spawn:
            return False, f"spawn overrides {sorted(spawn)}"
        unsafe = set(self.default_spawn) - self._PACK_SAFE_DEFAULT_SPAWN
        if unsafe:
            return False, (
                f"service default_spawn overrides {sorted(unsafe)} "
                "(the packed engine cannot honor them)"
            )
        opts = options or {}
        if opts.get("symmetry"):
            return False, "symmetry reduction (orbit keys cannot salt)"
        if opts.get("target_state_count"):
            return False, "target_state_count (per-wave overshoot cap)"
        if hbm_budget_mib is not None:
            return False, "hbm_budget_mib (solo tiered run)"
        return True, None

    def _classify_packable_swarm(self, *, aot_namespace, options, spawn):
        """Swarm packability: lane blocks over one stacked dispatch
        (``checker/swarm.SwarmPackedEngine``). Per-tenant depth caps
        and state targets are runtime scalars, so — unlike exhaustive
        packing — they do NOT disqualify; only a fleet-shape override
        or symmetry does."""
        if not self.packing:
            return False, "packing disabled on this service"
        if aot_namespace is None:
            return False, "custom model (no AOT namespace to pack under)"
        if spawn:
            return False, (
                f"spawn overrides {sorted(spawn)} (a packed swarm "
                "shares one fleet shape)"
            )
        if (options or {}).get("symmetry"):
            return False, "symmetry (host-only for walk cycle checks)"
        return True, None

    # -- durable recovery (service_dir mode) --------------------------------

    def _durable_spec(self, job: CheckJob) -> Optional[dict]:
        """The JSON-safe resubmission spec for one job, or None when the
        job cannot be journaled (a custom ``model_factory`` has no
        serializable identity — surfaced honestly as ``durable: false``
        instead of silently losing the job in a crash)."""
        if job.mode == "conformance":
            # The canonical wire lines ARE the job's identity: decoding
            # is deterministic, so a journal-resubmitted incarnation
            # audits the exact same records (bit-identical verdicts).
            spec = {
                "mode": "conformance",
                "records": list(getattr(job, "_conformance_lines", [])),
                "spawn": job.spawn or None,
                "priority": job.priority,
                "deadline_s": job.deadline_s,
                "tenant": job.tenant,
                "timeout_s": job.timeout_s,
                "retry_policy": (
                    job.retry_policy.to_dict()
                    if job.retry_policy is not None
                    else None
                ),
            }
            try:
                json.dumps(spec)
            except (TypeError, ValueError):
                return None
            return spec
        if job.model_name is None:
            return None
        spec = {
            "model_name": job.model_name,
            "model_args": getattr(job, "_journal_model_args", None),
        }
        spec.update(
            options=job.options or None,
            spawn=job.spawn or None,
            mode=job.mode,
            seed=job.seed,
            priority=job.priority,
            deadline_s=job.deadline_s,
            tenant=job.tenant,
            hbm_budget_mib=job.hbm_budget_mib,
            timeout_s=job.timeout_s,
            retry_policy=(
                job.retry_policy.to_dict()
                if job.retry_policy is not None
                else None
            ),
        )
        try:
            json.dumps(spec)
        except (TypeError, ValueError):
            return None
        return spec

    def _journal_write(self, record: dict) -> None:
        if self._journal_fh is None:
            return
        try:
            line = json.dumps(record, sort_keys=True)
        except (TypeError, ValueError):
            return
        with self._journal_lock:
            try:
                self._journal_fh.write(line + "\n")
                self._journal_fh.flush()
            except (OSError, ValueError):
                # A dead journal degrades durability, not the service.
                self._m_ckpt_errors.inc()

    def _journal_submit(self, job: CheckJob) -> None:
        if self._journal_fh is None:
            return
        spec = self._durable_spec(job)
        job.durable = spec is not None
        self._journal_write({
            "ev": "submit",
            "t": time.time(),
            "job_id": job.job_id,
            "durable": job.durable,
            "spec": spec,
        })

    def _journal_state(self, job: CheckJob) -> None:
        """One WAL line per externally-meaningful transition (suspend /
        fault / terminal): recover() replays these to rebuild the
        queue."""
        if self._journal_fh is None:
            return
        record = {
            "ev": "state",
            "t": time.time(),
            "job_id": job.job_id,
            "state": job.state,
            "preempts": job.preempts,
            "retries": job.retries,
            "error": job.error,
        }
        if job.state == JOB_DONE and isinstance(job.result, dict):
            # The finished-job record recover() must reconstruct: the
            # scalar verdict plus the golden report (bit-identity
            # evidence) — the heavy ledgers stay in memory only.
            record["result"] = {
                k: job.result.get(k)
                for k in (
                    "unique", "states", "max_depth", "properties_hold",
                    "rate", "report", "discoveries",
                )
            }
        self._journal_write(record)

    def _checkpoint_path_for(self, job_id: str) -> Optional[str]:
        if self.service_dir is None:
            return None
        return os.path.join(self.service_dir, "jobs", f"{job_id}.ckpt")

    def _checkpoint_job(self, job: CheckJob) -> None:
        """Atomic per-job durable checkpoint (rides ``atomic_pickle``):
        written at every suspend/fault boundary so a process crash
        resumes the job from its last good wave boundary instead of
        from scratch. Best-effort — a failed write degrades durability
        and counts ``service.recovery.checkpoint_errors``, it never
        fails the job."""
        path = self._checkpoint_path_for(job.job_id)
        if path is None or job.payload is None or not job.durable:
            return
        from ..checker.tpu import atomic_pickle

        try:
            atomic_pickle(path, job.payload)
        except Exception:  # noqa: BLE001 - durability is best-effort
            self._m_ckpt_errors.inc()

    def _drop_checkpoint(self, job_id: str) -> None:
        path = self._checkpoint_path_for(job_id)
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass

    @classmethod
    def recover(cls, service_dir: str, **kwargs) -> "CheckService":
        """Rebuilds a service from its crash remains: replays the WAL
        journal, reconstructs finished/failed/quarantined job records
        (handles keep answering), and RESUBMITS every unfinished
        durable job under its original id — resuming from its last
        durable checkpoint pickle when one exists, from scratch
        otherwise (both bit-identical to an uninterrupted run).
        Unfinished jobs that were submitted as ``durable: false`` are
        surfaced as failed records, never silently dropped."""
        import pickle

        journal_path = os.path.join(service_dir, "journal.jsonl")
        records: List[dict] = []
        if os.path.exists(journal_path):
            with open(journal_path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail from the crash itself
        svc = cls(service_dir=service_dir, **kwargs)
        # Gate the scheduler while payloads are being re-attached: a
        # resubmitted job must not be spawned before its checkpoint is
        # restored onto it (it would re-explore from scratch AND race
        # the payload write).
        svc._admission_hold = True
        from ..telemetry import metrics_registry

        reg = metrics_registry()
        c_restored = reg.counter("service.recovery.jobs_restored")
        c_resumed = reg.counter("service.recovery.jobs_resumed")
        c_lost = reg.counter("service.recovery.jobs_unrecoverable")
        reg.counter("service.recovery.journal_records").inc(len(records))

        submits: Dict[str, dict] = {}
        last_state: Dict[str, dict] = {}
        for rec in records:
            jid = rec.get("job_id")
            if rec.get("ev") == "submit":
                submits[jid] = rec
            elif rec.get("ev") == "state":
                last_state[jid] = rec
        for jid, sub in submits.items():
            state_rec = last_state.get(jid, {})
            state = state_rec.get("state", JOB_QUEUED)
            if state in (JOB_DONE, JOB_FAILED, JOB_CANCELLED,
                         JOB_QUARANTINED):
                # Terminal: reconstruct the record (no re-run).
                job = CheckJob(
                    jid, lambda: None,
                    model_name=(sub.get("spec") or {}).get("model_name"),
                    seq=next(svc._seq), clock=svc._clock,
                )
                job.durable = bool(sub.get("durable"))
                job.state = state
                job.preempts = int(state_rec.get("preempts") or 0)
                job.retries = int(state_rec.get("retries") or 0)
                job.result = state_rec.get("result")
                job.error = state_rec.get("error")
                job.finished_t = svc._clock()
                job.done_event.set()
                with svc._cond:
                    svc._jobs[jid] = job
                c_restored.inc()
                continue
            if not sub.get("durable") or not sub.get("spec"):
                # An unfinished non-journalable job: lost with the
                # process, and said so.
                job = CheckJob(
                    jid, lambda: None, seq=next(svc._seq),
                    clock=svc._clock,
                )
                job.state = JOB_FAILED
                job.error = (
                    "lost in service crash: submitted with a custom "
                    "model (durable: false), cannot be re-spawned from "
                    "the journal"
                )
                job.finished_t = svc._clock()
                job.done_event.set()
                with svc._cond:
                    svc._jobs[jid] = job
                c_lost.inc()
                continue
            spec = dict(sub["spec"])
            retry = spec.pop("retry_policy", None)
            # Replay bypasses the admission bound: these jobs were
            # already admitted before the crash — bouncing the backlog
            # overflow with QueueFullError mid-replay would abort the
            # very recovery the journal exists for.
            saved_limit, svc.max_queued_jobs = svc.max_queued_jobs, None
            try:
                retry_kw = (
                    RetryPolicy.from_dict(retry)
                    if retry is not None
                    else None
                )
                if spec.get("mode") == "conformance":
                    handle = svc.submit(
                        conformance=spec.pop("records"),
                        job_id=jid,
                        retry_policy=retry_kw,
                        **{
                            k: v for k, v in spec.items() if v is not None
                        },
                    )
                else:
                    handle = svc.submit(
                        model_name=spec.pop("model_name"),
                        model_args=spec.pop("model_args", None) or {},
                        job_id=jid,
                        retry_policy=retry_kw,
                        **{
                            k: v for k, v in spec.items() if v is not None
                        },
                    )
            except (ValueError, RuntimeError) as e:
                # One rotten journal entry must not abort the rest of
                # the replay — surface it as an explicit failed record.
                job = CheckJob(
                    jid, lambda: None,
                    model_name=(sub.get("spec") or {}).get("model_name"),
                    seq=next(svc._seq), clock=svc._clock,
                )
                job.state = JOB_FAILED
                job.error = f"journal replay failed: {e!r}"
                job.finished_t = svc._clock()
                job.done_event.set()
                with svc._cond:
                    svc._jobs.setdefault(jid, job)
                c_lost.inc()
                continue
            finally:
                svc.max_queued_jobs = saved_limit
            job = svc.job(handle.job_id)
            job.preempts = int(state_rec.get("preempts") or 0)
            job.retries = int(state_rec.get("retries") or 0)
            ckpt = svc._checkpoint_path_for(jid)
            if ckpt and os.path.exists(ckpt):
                try:
                    with open(ckpt, "rb") as f:
                        job.payload = pickle.load(f)
                    job.state = JOB_SUSPENDED
                except Exception:  # noqa: BLE001 - corrupt ckpt = restart
                    svc._m_ckpt_errors.inc()
            c_resumed.inc()
        svc._admission_hold = False
        svc._wake()
        return svc

    # -- introspection ------------------------------------------------------

    def job(self, job_id: str) -> Optional[CheckJob]:
        with self._cond:
            return self._jobs.get(job_id)

    def jobs(self) -> List[CheckJob]:
        with self._cond:
            return sorted(self._jobs.values(), key=lambda j: j.seq)

    def status(self) -> dict:
        js = self.jobs()
        out = {
            "quantum_s": self.quantum_s,
            "closing": self._closing.is_set(),
            "jobs": [j.status() for j in js],
            "counts": {
                state: sum(1 for j in js if j.state == state)
                for state in (
                    JOB_QUEUED, JOB_RUNNING, JOB_SUSPENDED, JOB_FAULTED,
                    JOB_DONE, JOB_FAILED, JOB_CANCELLED, JOB_QUARANTINED,
                )
            },
        }
        out["warm_start"] = {
            "enabled": self.warm_start and self.aot_store is not None,
        }
        if self.warm_pool_status:
            out["warm_start"]["pool"] = {
                ns: dict(entry)
                for ns, entry in self.warm_pool_status.items()
            }
        return out

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    # -- the scheduler loop -------------------------------------------------

    def _pick(self) -> Optional[CheckJob]:
        """Highest-priority runnable job (the admission order
        ``CheckJob.sort_key``); reaps cancelled queued jobs in passing.
        Caller holds the condition lock."""
        if self._admission_hold:
            return None
        best = None
        for job in self._jobs.values():
            if not job.runnable():
                continue
            if job.cancel_event.is_set():
                job.payload = None
                job.finish(JOB_CANCELLED)
                continue
            if best is None or job.sort_key() < best.sort_key():
                best = job
        return best

    def _should_preempt_for_peer(self, current: CheckJob) -> bool:
        """Whether suspending the current job at quantum expiry would
        actually hand the device to someone else: some other runnable
        job must sort AHEAD of where the current job would re-enter the
        queue (its round-robin clock stamped to "just ran"). Comparing
        the real sort keys — not just priority — keeps EDF jobs honest
        too: a finite-deadline job sorts first within its class
        regardless of recency, so a priority-only guard would preempt
        it every quantum only to re-pick it (pure checkpoint/restore
        churn) while its peers starve behind the respawn overhead."""
        current_key = current.sort_key(last_run_override=self._clock())
        with self._cond:
            return any(
                j is not current
                and j.runnable()
                and not j.cancel_event.is_set()
                and j.sort_key() < current_key
                for j in self._jobs.values()
            )

    def _run_scheduler(self) -> None:
        while True:
            with self._cond:
                job = self._pick()
                while job is None and not self._closing.is_set():
                    self._cond.wait(timeout=0.5)
                    job = self._pick()
                if self._closing.is_set():
                    return
            try:
                if self.packing and job.packable:
                    self._run_packed_slice(job)
                else:
                    self._run_slice(job)
            except Exception as e:  # noqa: BLE001 - a job must not kill the loop
                # Scheduler-infrastructure faults route through the
                # retry policy like slice faults — with the real
                # traceback attached, never a bare repr.
                if job.state not in (
                    JOB_DONE, JOB_FAILED, JOB_CANCELLED, JOB_QUARANTINED,
                    JOB_SUSPENDED, JOB_FAULTED,
                ):
                    self._fault_job(job, e)
            self._evict_finished()

    # -- fault routing (the self-healing core) ------------------------------

    def _fault_job(self, job: CheckJob, exc: BaseException,
                   checker=None, snapshot: Optional[dict] = None) -> None:
        """Routes one slice fault into the retry machinery: classify
        the fault, harvest the best resume payload available (a preempt
        payload the dying checker managed to yield beats the pre-slice
        snapshot beats from-scratch), and let the job's policy decide
        faulted/quarantined/failed. Metrics + journal + durable
        checkpoint ride every outcome."""
        fault_class = classify_fault(exc)
        tb = _format_exc(exc)
        payload = None
        digest = None
        if checker is not None:
            try:
                payload = checker.preempt_payload()
            except Exception:  # noqa: BLE001 - harvest is best-effort
                payload = None
            try:
                digest = checker.state_digest()
            except Exception:  # noqa: BLE001
                digest = None
        if payload is None:
            payload = snapshot
        self._m_faults.inc()
        self._fault_class_counter(fault_class).inc()
        state = job.fault(
            fault_class, repr(exc), tb, payload=payload, digest=digest
        )
        if state == JOB_FAULTED:
            self._m_retries.inc()
            self._checkpoint_job(job)
        else:
            # Terminal (quarantined, or failed for a non-retryable
            # class): the durable checkpoint must not outlive the job.
            if state == JOB_QUARANTINED:
                self._m_quarantined.inc()
            self._drop_checkpoint(job.job_id)
        self._journal_state(job)

    def _spawn(self, job: CheckJob):
        if job.mode == "conformance":
            from ..conformance.checker import ConformanceChecker

            sp = job.spawn or {}
            checker = ConformanceChecker(
                job._conformance_records,
                self.zoo,
                run_id=job.run_id,
                batch_lanes=int(sp.get("batch_lanes", 64)),
                parity=bool(sp.get("parity", False)),
                resume_from=job.payload,
                tenant=job.tenant,
            )
            job.payload = None
            return checker
        if job.mode == "swarm":
            # Per-namespace instance, not a fresh factory() call: the
            # swarm wave-executable cache pins the model by IDENTITY, so
            # a solo swarm job's compile-free second run (and every
            # preempted job's next incarnation) depends on same-config
            # spawns sharing one instance, exactly like the pack path.
            model = self._model_for(job.model_factory, job.aot_namespace)
        else:
            # Exhaustive solo jobs keep their own instance: their AOT
            # sharing is namespace+trace-signature keyed (identity-free),
            # and sharing here would let a user-supplied namespace that
            # lies about the configuration silently swap the model.
            model = job.model_factory()
        builder = model.checker()
        opts = job.options
        if opts.get("target_state_count"):
            builder = builder.target_state_count(opts["target_state_count"])
        if opts.get("target_max_depth"):
            builder = builder.target_max_depth(opts["target_max_depth"])
        if opts.get("symmetry"):
            builder = builder.symmetry()
        if job.mode == "swarm":
            # Swarm jobs spawn the device-resident walker regardless of
            # the service's exhaustive spawn_method; their spawn surface
            # is the fleet shape, not the BFS knobs.
            spawn = dict(self.default_swarm_spawn)
            spawn.update(job.spawn)
            spawn["run_id"] = job.run_id
            if job.aot_namespace is not None:
                spawn.setdefault(
                    "aot_cache", f"swarm:{job.aot_namespace}"
                )
            if job.payload is not None:
                spawn["resume_from"] = job.payload
                job.payload = None
            return builder.spawn_swarm(seed=job.seed, **spawn)
        if opts.get("complete_liveness"):
            builder = builder.complete_liveness(
                budget_states=opts.get("liveness_budget_states"),
                deadline_s=opts.get("liveness_deadline_s"),
            )
        spawn = dict(self.default_spawn)
        spawn.update(job.spawn)
        if (
            spawn.get("liveness") == "device"
            and self.spawn_method not in _DEVICE_LIVENESS_SPAWNS
        ):
            # Honest downgrade (job.liveness_reason says so): the
            # backend cannot honor the knob; passing it through would
            # fail the job on a TypeError instead.
            spawn.pop("liveness", None)
        if (
            job.derived_table_capacity is not None
            and "table_capacity" not in job.spawn
        ):
            # The tenant's budget, not the fixed default, sizes its
            # device table (see _validate_budget).
            spawn["table_capacity"] = job.derived_table_capacity
        spawn["run_id"] = job.run_id
        # Cross-job executable sharing is a single-device-checker
        # feature for now (the sharded checker has no aot_cache knob);
        # passing it unconditionally would TypeError every job under
        # spawn_method="spawn_sharded_tpu_bfs".
        if (
            job.aot_namespace is not None
            and self.spawn_method == "spawn_tpu_bfs"
        ):
            spawn.setdefault("aot_cache", job.aot_namespace)
        # Persistent AOT plane: the disk store rides along wherever a
        # checker can use it — the solo checker needs a namespace (its
        # in-memory shared cache keys on it); the sharded checker derives
        # its own namespace internally.
        if self.aot_store is not None:
            if (
                self.spawn_method == "spawn_tpu_bfs"
                and spawn.get("aot_cache") is not None
            ) or self.spawn_method == "spawn_sharded_tpu_bfs":
                spawn.setdefault("aot_store", self.aot_store)
        if job.hbm_budget_mib is not None:
            spawn.setdefault("hbm_budget_mib", job.hbm_budget_mib)
        if job.payload is None:
            # Incremental re-checking: a finished run of this exact
            # model may have left a seed — attach it as a resume payload
            # so the run completes in O(verify), not O(explore).
            self._maybe_attach_seed(job, model, spawn, opts)
        if job.payload is not None:
            spawn["resume_from"] = job.payload
            job.payload = None
        method = getattr(builder, self.spawn_method)
        import inspect

        sig = inspect.signature(method)
        if not any(
            p.kind is p.VAR_KEYWORD for p in sig.parameters.values()
        ):
            # Host-engine spawn methods (spawn_bfs/dfs/...) take no
            # kwargs: drop the device-spawn defaults (run_id included —
            # their metrics land in the default registry) so the
            # degrade-gracefully branch below is actually reachable
            # instead of dying on a TypeError at spawn.
            spawn = {k: v for k, v in spawn.items() if k in sig.parameters}
        return method(**spawn)

    # -- warm-start plane (persistent AOT + incremental re-checking) --------

    _SEED_SPAWN_BLOCKERS = ("liveness", "resume_from")

    def _seedable(self, job: CheckJob, opts: dict) -> bool:
        """Whether this job's configuration is in the seed plane at all:
        solo exhaustive, full-space (no targets), safety-only. Liveness
        and swarm verdicts depend on more than the visited set; a
        targeted run's seed would silently shrink a later full run."""
        return (
            self.seed_store is not None
            and self.warm_start
            and not job.warm_pool
            and job.mode == "exhaustive"
            and self.spawn_method == "spawn_tpu_bfs"
            and not opts.get("target_state_count")
            and not opts.get("target_max_depth")
            and not opts.get("complete_liveness")
            and not any(job.spawn.get(k) for k in self._SEED_SPAWN_BLOCKERS)
        )

    def _seed_structure(self, job: CheckJob, model):
        """The (model-structure, params) signature, memoized on the job
        (it traces packed_step per action — cheap, but not free)."""
        cached = getattr(job, "_seed_structure_cache", None)
        if cached is not None:
            return cached
        from ..storage.persist import model_structure_signature

        structure = model_structure_signature(model)
        job._seed_structure_cache = structure
        return structure

    def _maybe_attach_seed(self, job: CheckJob, model, spawn: dict,
                           opts: dict) -> None:
        """Seeds a fresh submission from a persisted finished run of the
        same model signature: the checker restores the seed's visited
        tiers + exact counts and completes in O(verify). Every refusal
        path is the conservative fallback — the job simply runs cold."""
        if not self._seedable(job, opts):
            return
        if spawn.get("liveness"):
            # The merged spawn may carry a service-default liveness mode
            # the job dict doesn't — liveness verdicts depend on more
            # than the visited set, so they stay out of the seed plane.
            return
        try:
            structure = self._seed_structure(job, model)
        except Exception as e:  # noqa: BLE001 - seeding is an optimization
            job.warm_start_reason = f"signature failed: {e!r}"
            return
        artifact, reason = self.seed_store.load(structure["family"])
        if artifact is None:
            if not reason.startswith("no seed"):
                self._m_seed_refused.inc()
                job.warm_start_reason = reason
            return
        from ..storage.persist import (
            adapt_seed_checkpoint,
            seed_compatibility,
        )

        ckpt = artifact.get("checkpoint") or {}
        if bool(ckpt.get("symmetry")) != bool(opts.get("symmetry")):
            self._m_seed_refused.inc()
            job.warm_start_reason = (
                "symmetry mismatch between seed and submission"
            )
            return
        verdict = seed_compatibility(artifact, structure)
        if not verdict.get("compatible"):
            self._m_seed_refused.inc()
            job.warm_start_reason = verdict.get("reason", "incompatible")
            return
        try:
            payload = adapt_seed_checkpoint(artifact, model)
        except Exception as e:  # noqa: BLE001
            self._m_seed_refused.inc()
            job.warm_start_reason = f"seed adaptation failed: {e!r}"
            return
        counts = artifact.get("counts") or {}
        digest = structure["digest"]
        spawn["resume_from"] = payload
        job.warm_start = True
        job.seeded_from = {
            "signature": digest,
            "family": structure["family"],
            "mode": verdict.get("mode", "exact"),
            "runs": int(counts.get("runs", 0)),
            "keys": int(counts.get("keys", 0)),
            "unique": int(counts.get("unique", 0)),
            "invalidated_uniques": int(
                verdict.get("invalidated_uniques", 0)
            ),
        }
        # Honest capability surfacing: the reporter names the seed so a
        # verdict reader knows this run re-verified a persisted space.
        notes = list(spawn.get("config_notes") or ())
        notes.append(
            f"warm-start: seeded from persisted run {digest[:12]} "
            f"(mode={job.seeded_from['mode']}, "
            f"runs={job.seeded_from['runs']}, "
            f"keys={job.seeded_from['keys']})"
        )
        spawn["config_notes"] = notes
        self._m_seed_loaded.inc()

    def _save_seed(self, job: CheckJob, checker) -> None:
        """Persists a finished full exhaustive run as a warm-start seed.
        Strictly an optimization: every failure is swallowed (the
        verdict is already complete), and an already-seeded job's space
        is content-identical to its seed, so re-saving is skipped."""
        if job.warm_start or not self._seedable(job, job.options):
            return
        if getattr(checker, "_live_enabled", False):
            return
        try:
            from ..storage.persist import build_seed_artifact

            structure = self._seed_structure(job, checker._model)
            payload = checker.checkpoint_payload([])
            artifact = build_seed_artifact(
                structure,
                payload,
                coverage=(job.result or {}).get("coverage"),
            )
            if self.seed_store.save(artifact) is not None:
                self._m_seed_saved.inc()
        except Exception:  # noqa: BLE001 - seeds never gate verdicts
            pass

    def _warm_shapes(self, warm_pool):
        """Normalizes the ``warm_pool=`` option into
        ``(namespace, model_name, model_args)`` triples."""
        if warm_pool is True:
            from .zoo import warm_shapes as zoo_warm_shapes

            pairs = zoo_warm_shapes()
        else:
            pairs = [
                (name, dict(args or {})) for name, args in warm_pool
            ]
        out = []
        for name, args in pairs:
            if name not in self.zoo:
                continue
            out.append((zoo_namespace(name, args), name, args))
        return out

    def _warm_pool_worker(self, shapes) -> None:
        """Pre-compiles each registered shape by running it as a
        rock-bottom-priority depth-2 job: ``target_max_depth`` keeps the
        deep drain enabled and is excluded from the AOT signature, so
        the warm run compiles (and disk-persists) the exact wave+drain
        executables real jobs of that shape will request."""
        for ns, name, args in shapes:
            if self._closing.is_set():
                break
            entry = self.warm_pool_status[ns]
            try:
                handle = self.submit(
                    model_name=name,
                    model_args=args,
                    options={"target_max_depth": 2},
                    priority=-(2**20),
                    _warm_pool=True,
                )
                entry["job_id"] = handle.job_id
                handle.result(timeout=600.0)
                self._warm_conformance(ns, name, args)
                entry["state"] = "ready"
            except Exception as e:  # noqa: BLE001 - warmth is best-effort
                entry["state"] = "failed"
                entry["error"] = repr(e)
            ready = sum(
                1 for s in self.warm_pool_status.values()
                if s["state"] == "ready"
            )
            pending = sum(
                1 for s in self.warm_pool_status.values()
                if s["state"] == "pending"
            )
            self._g_pool_ready.set(ready)
            self._g_pool_pending.set(pending)

    def _warm_conformance(self, ns: str, name: str, args: dict) -> None:
        """Conformance-plane warm-pool registration: the replay
        executable for this zoo shape, compiled (and executed once on
        an inert batch) at the default batch geometry, so a first
        conformance upload of a warm shape replays without the
        trace+compile stall. Best-effort, like the rest of the pool."""
        try:
            from ..conformance.replay import warm_replay

            factory = self.zoo[name]
            model = self._model_for(lambda: factory(**args), ns)
            warm_replay(
                model, ns, _CONFORMANCE_WARM_T, _CONFORMANCE_WARM_L
            )
        except Exception:  # noqa: BLE001 - warmth is best-effort
            pass

    def _poll_discoveries(self, job: CheckJob, checker) -> None:
        try:
            names = set(checker._discovery_names())
        except Exception:  # noqa: BLE001 - mid-run best effort
            return
        fresh = names - job.seen_discoveries
        if fresh:
            job.seen_discoveries |= names
            if job.first_discovery_t is None:
                job.first_discovery_t = self._clock()

    def _timed_out(self, job: CheckJob) -> bool:
        return (
            job.timeout_s is not None
            and self._clock() - job.submitted_t >= job.timeout_s
        )

    def _fail_timeout(self, job: CheckJob, checker=None,
                      view_digest=None) -> None:
        """Wall-clock timeout: the job fails WITH partial-progress
        evidence (how far it got, and whether a resumable payload
        existed) — an operator must be able to tell a hung model from
        an under-provisioned deadline."""
        digest = view_digest
        if digest is None and checker is not None:
            try:
                digest = checker.state_digest()
            except Exception:  # noqa: BLE001 - evidence is best-effort
                digest = None
        self._m_timeouts.inc()
        job.fail(
            f"timeout: exceeded timeout_s={job.timeout_s} "
            f"(wall {self._clock() - job.submitted_t:.1f}s)",
            flight={
                "reason": "timeout",
                "partial_progress": digest,
                "preempts": job.preempts,
                "slices": job.slices,
                "resumable_payload": job.payload is not None
                or (checker is not None and checker.preempted),
            },
        )
        self._journal_state(job)
        self._drop_checkpoint(job.job_id)

    def _make_watchdog(self, job: CheckJob, checker):
        """The per-slice stall watchdog (telemetry/server.py's engine,
        polled inline — no extra thread): no progress for
        ``stall_deadline_s`` fires the action hook, whose default
        auto-preempts so the wedged job suspends at its next yield
        point and retries from that wave boundary."""
        if self.stall_deadline_s is None:
            return None
        from ..telemetry.server import StallWatchdog

        def action(idle_s):
            self._m_stall_preempts.inc()
            job.stall_preempts += 1
            if self.on_stall is not None:
                self.on_stall(job, checker, idle_s)
            else:
                try:
                    checker.request_preempt()
                except NotImplementedError:
                    pass

        return StallWatchdog(
            self.stall_deadline_s,
            clock=self._clock,
            on_stall=action,
            done_fn=checker.is_done,
        )

    def _run_slice(self, job: CheckJob) -> None:
        """One scheduling slice: (re)spawn the job's checker, let it run
        for up to a quantum (to completion when nothing else wants the
        device), then preempt/harvest. Strictly serialized — the device
        has exactly one claimant at any time."""
        job.state = JOB_RUNNING
        job.slices += 1
        # Snapshot the resume payload BEFORE _spawn consumes it: a
        # faulted slice hands this back so the retry resumes from the
        # last good wave boundary instead of re-exploring from scratch.
        resume_snapshot = job.payload
        t0 = self._clock()
        if job.started_t is None:
            job.started_t = t0
        try:
            checker = self._spawn(job)
        except Exception as e:  # noqa: BLE001 - bad knobs/model = job failure
            # Spawn-time errors are configuration, not transient faults:
            # no retry, but the real traceback survives.
            job.fail(repr(e), _format_exc(e))
            self._journal_state(job)
            self._drop_checkpoint(job.job_id)
            return
        self._active_checker = checker
        # Honest preemptibility: the admission-time guess (spawn-method
        # map) corrected from the live checker's own declaration.
        job.preemptible = bool(getattr(checker, "supports_preempt", False))
        # On resume, the restored discoveries must not count as "first".
        self._poll_discoveries(job, checker)
        slice_end = t0 + self.quantum_s
        watchdog = self._make_watchdog(job, checker)
        progress_mark = None

        # A backend without preemption support (host engines raise
        # NotImplementedError from the base request_preempt) degrades
        # gracefully: its slice simply runs to completion — failing the
        # job while its worker threads live on would leave TWO checkers
        # claiming the device once the scheduler moved on.
        def try_preempt() -> bool:
            try:
                checker.request_preempt()
                return True
            except NotImplementedError:
                job.preemptible = False
                return False

        preempting = False
        preemptible = True
        timed_out = False
        stalled = False  # stall action fires at most once per slice
        try:
            while not checker.is_done():
                if (job.cancel_event.is_set() or self._closing.is_set()) \
                        and not preempting and preemptible:
                    preemptible = preempting = try_preempt()
                elif (
                    not preempting
                    and preemptible
                    and self._timed_out(job)
                ):
                    # Wall-clock budget blown: stop at the next wave
                    # boundary and fail with the partial progress.
                    timed_out = True
                    preemptible = preempting = try_preempt()
                elif (
                    not preempting
                    and preemptible
                    and self._clock() >= slice_end
                    and self._should_preempt_for_peer(job)
                ):
                    preemptible = preempting = try_preempt()
                self._poll_discoveries(job, checker)
                if watchdog is not None and not preempting and not stalled:
                    # Progress = counters moving, or the slice still in
                    # its compile/restore warmup (no waves CAN land yet
                    # — warmup must not read as a stall). The action
                    # hook fires at most once per slice: after an
                    # auto-preempt the slice is already on its way out,
                    # and refiring every poll would be pure churn.
                    mark = (
                        checker.state_count(),
                        checker.unique_state_count(),
                    )
                    if (
                        mark != progress_mark
                        or getattr(checker, "warmup_seconds", None) is None
                    ):
                        progress_mark = mark
                        watchdog.pet()
                    elif watchdog.poll():
                        stalled = True
                time.sleep(self.poll_interval_s)
            for h in checker.handles():
                h.join()
            self._poll_discoveries(job, checker)
        finally:
            self._active_checker = None
            job.active_s += self._clock() - t0
            job.last_run_t = self._clock()
            job.warmup_s += getattr(checker, "warmup_seconds", None) or 0.0
        err = checker.worker_error()
        if err is not None:
            self._fault_job(job, err, checker=checker,
                            snapshot=resume_snapshot)
            return
        if job.cancel_event.is_set():
            job.finish(JOB_CANCELLED)
            self._journal_state(job)
            self._drop_checkpoint(job.job_id)
            return
        if (timed_out or self._timed_out(job)) and checker.preempted:
            # Timeout is enforced at the next yield point; a run that
            # COMPLETED before it could be stopped keeps its verdict
            # (on a non-preemptible backend the deadline simply cannot
            # cut the slice — discarding a finished result would make
            # the outcome depend on which preempt attempt fired first).
            self._fail_timeout(job, checker=checker)
            return
        if checker.preempted:
            job.suspend(checker.preempt_payload())
            self._checkpoint_job(job)
            self._journal_state(job)
            return
        if job.retries:
            self._m_recovered.inc()
        job.complete(self._finalize(job, checker))
        self._save_seed(job, checker)
        if not job.warm_pool:
            self.slo.observe(job)
        self._journal_state(job)
        self._drop_checkpoint(job.job_id)

    # -- the packer (tenant-packed waves) -----------------------------------

    def _pack_peers(self, key: str, members: Dict[str, CheckJob],
                    mode: str = "exhaustive"):
        """Runnable packable same-configuration same-mode jobs not yet
        in the pack — the admission candidates, best-first. (A swarm
        fleet and an exhaustive wave cannot share a dispatch.)"""
        with self._cond:
            peers = [
                j
                for j in self._jobs.values()
                if j.job_id not in members
                and j.runnable()
                and not j.cancel_event.is_set()
                and j.packable
                and j.aot_namespace == key
                and j.mode == mode
            ]
        return sorted(peers, key=lambda j: j.sort_key())

    def _pack_contender(self, key: str, members: Dict[str, CheckJob],
                        can_join: bool,
                        mode: str = "exhaustive") -> bool:
        """Whether a runnable job OUTSIDE the pack — one that cannot
        simply join it — sorts ahead of where the pack's best member
        would re-enter the queue. Same honesty rule as
        ``_should_preempt_for_peer``: suspending the pack must actually
        hand the device to someone else. A same-shape packable job
        counts as a contender too once the pack has no free lane
        (``can_join=False``) — otherwise a full pack would starve a
        higher-priority same-shape arrival that the time-slicer would
        have preempted for."""
        now = self._clock()
        reentry = min(
            j.sort_key(last_run_override=now) for j in members.values()
        )
        with self._cond:
            return any(
                j.job_id not in members
                and j.runnable()
                and not j.cancel_event.is_set()
                and not (
                    can_join
                    and j.packable
                    and j.aot_namespace == key
                    and j.mode == mode
                )
                and j.sort_key() < reentry
                for j in self._jobs.values()
            )

    def _pack_admit(self, engine, job: CheckJob):
        """Claims a lane slot for one job (restoring its suspended
        payload slice, if any); stamps the membership clocks only AFTER
        the admission succeeds — a failed admit must not leave the job
        reporting packed:true with a counted slice."""
        if job.mode == "swarm":
            view = engine.admit(
                job.job_id,
                job.run_id,
                seed=job.seed,
                depth_cap=job.options.get("target_max_depth"),
                target_state_count=job.options.get("target_state_count"),
                resume_from=job.payload,
            )
        else:
            view = engine.admit(
                job.job_id,
                job.run_id,
                depth_cap=job.options.get("target_max_depth"),
                resume_from=job.payload,
            )
        job.payload = None
        job.state = JOB_RUNNING
        job.slices += 1
        job.packed = True
        now = self._clock()
        if job.started_t is None:
            job.started_t = now
        job.pack_join_t = now
        # Restored discoveries must not count as "first" for ttfv.
        try:
            job.seen_discoveries |= set(view._discovery_names())
        except Exception:  # noqa: BLE001 - best effort
            pass
        return view

    def _try_pack_admit(self, engine, job, members, views,
                        snapshots) -> bool:
        # The pre-admit payload is the job's last checkpointed boundary:
        # a later engine-wide fault retries the member from here (the
        # honest fallback when the pack's own state cannot be trusted).
        snapshot = job.payload
        try:
            view = self._pack_admit(engine, job)
        except Exception as e:  # noqa: BLE001 - admit faults route to retry
            job.payload = snapshot
            self._fault_job(job, e, snapshot=snapshot)
            return False
        members[job.job_id] = job
        views[job.job_id] = view
        snapshots[job.job_id] = snapshot
        return True

    def _pack_leave(self, job: CheckJob, view) -> None:
        """Membership clocks on any exit (complete/suspend/cancel)."""
        now = self._clock()
        job.active_s += now - (job.pack_join_t or now)
        job.pack_join_t = None
        job.last_run_t = now
        job.warmup_s += getattr(view, "warmup_seconds", None) or 0.0

    def _suspend_pack(self, engine, members, views) -> None:
        """Drops every member's lanes (no device drain): each hands back
        its survivors as a checkpoint-v2 payload slice and re-enters the
        admission queue suspended."""
        for jid, job in list(members.items()):
            # A cancelled member's payload would be thrown away —
            # discard up front instead of building the full parent-map
            # export on the scheduler thread.
            cancelled = job.cancel_event.is_set()
            payload = engine.drop(jid, discard=cancelled)
            self._pack_leave(job, views[jid])
            if cancelled:
                job.payload = None
                job.finish(JOB_CANCELLED)
                self._drop_checkpoint(jid)
            else:
                job.suspend(payload)
                self._checkpoint_job(job)
            self._journal_state(job)
        members.clear()
        views.clear()

    def _run_packed_slice(self, lead: CheckJob) -> None:
        """One packed slice: every runnable same-configuration packable
        job co-schedules onto one ``TenantPackedEngine`` — shared waves,
        per-tenant lane accounting. Late same-shape arrivals JOIN the
        live pack (admission = claim a free lane slot); a member's
        cancel drops only its lanes; quantum expiry suspends the pack
        only when an outside contender would actually be picked.
        Strictly serialized with every other slice — the device still
        has exactly one claimant."""
        key = lead.aot_namespace
        mode = lead.mode
        spawn = dict(self.default_spawn)
        model = self._model_for(lead.model_factory, key)
        founders = [lead, *self._pack_peers(key, {}, mode)]
        if mode == "swarm":
            # Swarm packs: lane blocks over one stacked walk dispatch —
            # no shared table, no salting; every tenant's verdict is
            # the solo run's by vmap construction (checker/swarm.py).
            from ..checker.swarm import SwarmPackedEngine

            engine = SwarmPackedEngine(
                model,
                max_tenants=self.max_pack_tenants,
                aot_cache=f"swarmpack:{key}",
                **self.default_swarm_spawn,
            )
        else:
            from ..checker.packed_tenancy import TenantPackedEngine

            base_table = spawn.get("table_capacity", 1 << 16)
            # Size the shared table for the founding fleet up front: K
            # tenants' visited sets share one table, and pre-sizing
            # avoids the growth rehashes (and their per-shape compiles)
            # a per-tenant-sized table would churn through mid-pack.
            m = 1
            while m < min(len(founders), self.max_pack_tenants):
                m *= 2
            engine = TenantPackedEngine(
                model,
                frontier_capacity=spawn.get("frontier_capacity", 1 << 10),
                table_capacity=base_table * m,
                max_tenants=self.max_pack_tenants,
                # Packed waves are occupancy-dense by construction (that
                # is the point of packing) — the bucket ladder would
                # only buy a compile shape per rung for the few ramp-up
                # waves.
                bucket_ladder=0,
                aot_cache=f"pack:{key}",
                resume_capacity=base_table,
                # The service knob, or a service-wide async default (a
                # pack-safe default_spawn key) — either opts the pack's
                # host half onto the pipeline worker.
                async_pipeline=(
                    self.pack_async
                    or bool(spawn.get("async_pipeline"))
                ),
                # Pack-safe service-wide knob: per-tenant edge
                # partitions keep each member's verdict identical to
                # its solo run's.
                liveness=spawn.get("liveness"),
            )
        members: Dict[str, CheckJob] = {}
        views: Dict[str, object] = {}
        snapshots: Dict[str, Optional[dict]] = {}
        self._active_checker = engine
        slice_end = self._clock() + self.quantum_s
        try:
            for job in founders:
                if engine.free_slots() == 0:
                    break
                if job.job_id not in members:
                    self._try_pack_admit(
                        engine, job, members, views, snapshots
                    )
            while members and engine.live_count():
                if self._closing.is_set():
                    self._suspend_pack(engine, members, views)
                    return
                for jid, job in list(members.items()):
                    if job.cancel_event.is_set():
                        engine.drop(jid, discard=True)
                        self._pack_leave(job, views.pop(jid))
                        members.pop(jid)
                        job.payload = None
                        job.finish(JOB_CANCELLED)
                        self._journal_state(job)
                        self._drop_checkpoint(jid)
                    elif self._timed_out(job):
                        # Per-member wall-clock enforcement: only this
                        # tenant's lanes drop; the pack keeps going.
                        digest = None
                        try:
                            digest = views[jid].state_digest()
                        except Exception:  # noqa: BLE001
                            pass
                        engine.drop(jid, discard=True)
                        self._pack_leave(job, views.pop(jid))
                        members.pop(jid)
                        self._fail_timeout(job, view_digest=digest)
                if not members:
                    return
                if engine.free_slots():
                    for job in self._pack_peers(key, members, mode):
                        if engine.free_slots() == 0:
                            break
                        self._try_pack_admit(
                            engine, job, members, views, snapshots
                        )
                if (
                    self._clock() >= slice_end
                    and self._pack_contender(
                        key, members, engine.free_slots() > 0, mode
                    )
                ):
                    self._suspend_pack(engine, members, views)
                    return
                try:
                    done_keys = engine.step()
                except Exception as e:  # noqa: BLE001 - routed below
                    tf = tenant_fault_of(e)
                    if (
                        tf is not None
                        and tf.tenant_key in members
                        # Swarm packs have no async host half — tenant
                        # attribution holds regardless of pack_async.
                        and (mode == "swarm" or not self.pack_async)
                    ):
                        # PACK-LOCAL BLAST RADIUS: the engine rolled
                        # every faulted tenant back to its pre-wave
                        # boundary, so each lane drop hands back an
                        # exact payload slice; the survivors keep
                        # expanding in this very loop. One pass can
                        # fault SEVERAL tenants (e.g. an eviction
                        # sweep), so drop all flagged ones — a flagged
                        # tenant left resident is unschedulable yet
                        # counts live, which would spin this loop
                        # forever.
                        faulted = [tf.tenant_key] + [
                            k
                            for k in engine.faulted_keys()
                            if k != tf.tenant_key
                        ]
                        for jid in faulted:
                            if jid not in members:
                                continue
                            # Each co-faulted tenant routes its OWN
                            # exception (retry_on filtering and the
                            # flight dump must not read another
                            # tenant's error).
                            exc = engine.fault_error(jid) or e
                            job = members.pop(jid)
                            view = views.pop(jid)
                            try:
                                payload = engine.drop(jid)
                            except Exception:  # noqa: BLE001 - fallback
                                payload = snapshots.get(jid)
                            self._pack_leave(job, view)
                            # Conservative: the retried tenant runs
                            # solo (time-sliced) instead of re-joining
                            # the pack it just faulted out of.
                            job.packable = False
                            job.packable_reason = (
                                "faulted in a pack; retrying solo"
                            )
                            self._fault_job(job, exc, snapshot=payload)
                        continue
                    # Non-attributable engine fault (or async mode,
                    # where the poisoned pipeline skipped later
                    # tenants' verdicts so no drop payload can be
                    # trusted): every member retries SOLO from its
                    # last checkpointed boundary — suspended work is
                    # re-explored, never corrupted.
                    for jid, job in list(members.items()):
                        self._pack_leave(job, views[jid])
                        job.packable = False
                        job.packable_reason = (
                            "pack engine fault; retrying solo"
                        )
                        self._fault_job(
                            job, e, snapshot=snapshots.get(jid)
                        )
                    members.clear()
                    views.clear()
                    return
                for done_key in done_keys:
                    job = members.pop(done_key)
                    view = views.pop(done_key)
                    # Final discovery sweep BEFORE completing: a
                    # discovery landing in the job's last wave must
                    # still stamp first_discovery_t (ttfv) — the solo
                    # path polls once more after join for the same
                    # reason.
                    self._poll_discoveries(job, view)
                    self._pack_leave(job, view)
                    engine.release(done_key)
                    if job.retries:
                        self._m_recovered.inc()
                    job.complete(self._finalize(job, view))
                    if not job.warm_pool:
                        self.slo.observe(job)
                    self._journal_state(job)
                    self._drop_checkpoint(done_key)
                for jid, job in members.items():
                    self._poll_discoveries(job, views[jid])
        except Exception as e:  # noqa: BLE001 - engine failure faults members
            if not members:
                raise
            for jid, job in list(members.items()):
                self._pack_leave(job, views.get(jid))
                self._fault_job(job, e, snapshot=snapshots.get(jid))
            members.clear()
        finally:
            self._active_checker = None
            engine.close()

    def _evict_finished(self) -> None:
        """Drops the oldest terminal jobs (and their run registries)
        past the retention cap, and — the tighter bound — the run
        registries of RETAINED terminal jobs past ``max_run_registries``
        (LRU by finish time). A registry-evicted job keeps its record
        and result (snapshotted on the job object); only its live
        instrument registry is forgotten, counted by
        ``service.registry_evicted``. Suspended/queued/running jobs are
        never evicted."""
        from ..telemetry import discard_run_registry
        from ..telemetry.metrics import run_registries

        with self._cond:
            finished = sorted(
                (
                    j
                    for j in self._jobs.values()
                    if j.state in (
                        JOB_DONE, JOB_FAILED, JOB_CANCELLED,
                        JOB_QUARANTINED,
                    )
                ),
                key=lambda j: j.finished_t or 0.0,
            )
            excess = finished[: max(0, len(finished) - self.max_finished_jobs)]
            for j in excess:
                del self._jobs[j.job_id]
        for j in excess:
            discard_run_registry(j.run_id)
        retained = finished[len(excess):]
        live = run_registries()
        with_reg = [j for j in retained if j.run_id in live]
        for j in with_reg[: max(0, len(with_reg) - self.max_run_registries)]:
            discard_run_registry(j.run_id)
            self._m_registry_evicted.inc()

    def _finalize(self, job: CheckJob, checker) -> dict:
        """The completed job's verdict record (the bench's per-job row)."""
        if (
            getattr(checker, "_complete_liveness", False)
            and getattr(checker, "_lasso_deadline_s", None) is None
            and self.stall_deadline_s is not None
        ):
            # Stall-watchdog wiring for the host lasso pass: it runs
            # inside discoveries() AFTER the last wave boundary, so the
            # auto-preempt hook has nothing left to preempt — instead
            # the watchdog's deadline bounds the pass itself, which then
            # yields an honest `inconclusive` (liveness.inconclusive
            # metric + reporter line) instead of wedging the scheduler
            # thread for unbounded host minutes.
            checker._lasso_deadline_s = self.stall_deadline_s
        unique = checker.unique_state_count()
        discoveries = {}
        try:
            for name, path in checker.discoveries().items():
                discoveries[name] = {
                    "classification": checker.discovery_classification(name),
                    "length": len(path),
                }
        except Exception as e:  # noqa: BLE001 - verdicts above all
            discoveries = {"error": repr(e)}
        try:
            checker.assert_properties()
            properties_hold = True
        except AssertionError:
            properties_hold = False
        out = io.StringIO()
        try:
            checker.report(WriteReporter(out))
        except Exception:  # noqa: BLE001
            pass
        steady = max(job.active_s - job.warmup_s, 1e-9)
        result = {
            "unique": unique,
            "states": checker.state_count(),
            "max_depth": checker.max_depth(),
            "discoveries": discoveries,
            "properties_hold": properties_hold,
            "report": out.getvalue(),
            "warmup_s": job.warmup_s,
            "rate": unique / steady,
        }
        attribution = checker.attribution_report()
        if attribution is not None:
            result["attribution"] = attribution
            # Compile seconds ACROSS incarnations: the final checker's
            # ledger only covers its own life, but the per-run registry's
            # `*.pipeline.compile_seconds` counters persist through
            # preempt/resume cycles — the honest shared-AOT-cache
            # evidence (a job that compiled in slice 1 and finished in a
            # cache-hitting slice 3 is NOT compile-free).
            try:
                snap = checker.metrics().snapshot()
                result["compile_s_total"] = sum(
                    v
                    for k, v in snap.items()
                    if k.endswith(".pipeline.compile_seconds")
                    and isinstance(v, (int, float))
                )
            except Exception:  # noqa: BLE001 - evidence, not verdict
                pass
        cov = checker.coverage_report()
        if cov is not None:
            result["coverage"] = cov
        try:
            # Disk-AOT evidence per job: run-registry counters persist
            # across incarnations, so these sum every slice's probes —
            # the bench's way to tell a disk hit from an in-memory hit.
            snap = checker.metrics().snapshot()
            aot = {
                key: int(v)
                for key, v in snap.items()
                if key.startswith("aot_cache.")
                and isinstance(v, (int, float))
            }
            if aot:
                result["aot"] = aot
        except Exception:  # noqa: BLE001 - evidence, not verdict
            pass
        if job.warm_start:
            result["warm_start"] = True
            result["seeded_from"] = job.seeded_from
        try:
            # Corrected from the live checker (the admission guess may
            # predate a downgrade), plus the per-property evidence.
            job.liveness_mode = checker.liveness_mode
            result["liveness"] = checker.liveness_report()
        except Exception:  # noqa: BLE001 - evidence, never the verdict
            pass
        conf = getattr(checker, "conformance_report", None)
        if conf is not None:
            # The conformance plane's verdict block: one verdict per
            # uploaded record, in upload order, plus batch accounting.
            result["conformance"] = conf()
        return result

    # -- lifecycle ----------------------------------------------------------

    def close(self, timeout: Optional[float] = 30.0) -> dict:
        """Stops the scheduler: the running slice (if any) is preempted
        at its next wave boundary and left suspended, queued jobs stay
        queued; in ``service_dir`` mode every suspended job's payload is
        flushed to its durable checkpoint. Idempotent.

        Returns ``{"closed": bool, "stuck": bool}``: a scheduler thread
        still alive after the join timeout is REPORTED (plus a
        ``service.close.stuck`` metric and a trace instant) instead of
        silently pretending the close succeeded — the caller may still
        be holding a wedged device slice."""
        self._closing.set()
        self._wake()
        self._scheduler.join(timeout=timeout)
        stuck = self._scheduler.is_alive()
        if stuck:
            self._m_close_stuck.inc()
            try:
                from ..telemetry import get_tracer

                get_tracer().instant(
                    "service.close.stuck", timeout_s=timeout
                )
            except Exception:  # noqa: BLE001 - diagnostics only
                pass
        # Durable flush: suspended payloads outlive the process only if
        # they are on disk. Safe even when stuck — suspended jobs are
        # not the one the scheduler is wedged on.
        if self.service_dir is not None:
            for job in self.jobs():
                if job.state in (JOB_SUSPENDED, JOB_FAULTED):
                    self._checkpoint_job(job)
                    self._journal_state(job)
            if not stuck:
                with self._journal_lock:
                    if self._journal_fh is not None:
                        try:
                            self._journal_fh.close()
                        except OSError:
                            pass
                        self._journal_fh = None
        return {"closed": not stuck, "stuck": stuck}

    def __enter__(self) -> "CheckService":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
