"""The persistent check scheduler: one device, many jobs.

``CheckService`` owns the accelerator the way a database owns its disk: a
scheduler thread admits :class:`CheckJob` s (priority high-first, EDF
within a priority, FIFO within a deadline) and time-slices the device
between them at **wave granularity** — a running job is suspended by
``TpuBfsChecker.request_preempt()`` (its wave state drains to a host-side
checkpoint payload at the next wave/drain boundary) and resumed later by
spawning a new checker with ``resume_from=<payload>``; the resumed run is
bit-identical to an uninterrupted one (counts, depths, discoveries,
golden reporter — tests/test_preempt_resume.py).

Jobs multiplex onto the shared AOT rung cache (``checker/tpu.py``'s
``shared_aot_cache``): two jobs of the same zoo configuration share every
``(bucket, table_capacity)`` wave/drain executable, so the second job —
and every preempted job's next incarnation — records zero compile phases
in its attribution ledger. Each job runs under its own ``run_id``: its
own metrics registry and run-stamped trace spans, so per-job ``/metrics``
/ ``/status`` / SSE / attribution / coverage all work (PR 3-8 plumbing).

Single-device by design: slices are strictly serialized, so the device
never has two claimants (the same constraint the bench's sentinel
coordination enforces across processes, here enforced by the scheduler
loop within one).
"""

from __future__ import annotations

import io
import itertools
import threading
import time
from typing import Callable, Dict, List, Optional

from ..report import WriteReporter
from .jobs import (
    JOB_CANCELLED,
    JOB_DONE,
    JOB_FAILED,
    JOB_QUEUED,
    JOB_RUNNING,
    JOB_SUSPENDED,
    CheckJob,
    JobHandle,
)
from .zoo import aot_namespace as zoo_namespace
from .zoo import default_zoo

# Builder options POST /jobs and submit(options=...) accept.
_BUILDER_OPTIONS = ("target_state_count", "target_max_depth", "symmetry")

# Spawn kwargs the service defaults for every job: a bounded drain cap is
# what makes preemption latency a few waves instead of a whole drain (the
# same clamp checkpoint durability applies), and modest capacities fit
# many tenants on one device.
_DEFAULT_SPAWN = {
    "frontier_capacity": 1 << 10,
    "table_capacity": 1 << 16,
    "max_drain_waves": 8,
}

# Default job ids are unique across every service in the process (the
# id is also the run_id, which keys process-global registries).
_GLOBAL_JOB_SEQ = itertools.count()


class CheckService:
    """A long-lived, in-process checking service.

    ::

        svc = CheckService()
        h1 = svc.submit(model_name="2pc", model_args={"rm_count": 5})
        h2 = svc.submit(model_name="abd", priority=1)   # runs first
        print(h1.result()["unique"], h1.status()["latency"]["ttfv_s"])
        svc.close()

    ``quantum_s`` is the scheduling quantum: a running job is preempted
    once its slice exceeds it *and* another job is runnable (a sole job
    runs uninterrupted — preemption exists for sharing, not ceremony).
    ``default_hbm_budget_mib`` is the per-tenant device budget applied to
    jobs that don't set their own (the PR 5 tiered store enforces it).
    """

    def __init__(
        self,
        *,
        quantum_s: float = 1.0,
        poll_interval_s: float = 0.005,
        zoo: Optional[Dict[str, Callable]] = None,
        default_spawn: Optional[dict] = None,
        default_hbm_budget_mib: Optional[float] = None,
        spawn_method: str = "spawn_tpu_bfs",
        max_finished_jobs: int = 256,
        clock=time.monotonic,
    ):
        self.quantum_s = float(quantum_s)
        self.poll_interval_s = float(poll_interval_s)
        self.zoo = dict(zoo) if zoo is not None else default_zoo()
        self.default_spawn = dict(_DEFAULT_SPAWN)
        if default_spawn:
            self.default_spawn.update(default_spawn)
        self.default_hbm_budget_mib = default_hbm_budget_mib
        self.spawn_method = spawn_method
        # Retention: terminal jobs (and their run registries) beyond
        # this count are evicted oldest-first, so a long-lived service
        # does not accrete one registry + result blob per finished job
        # forever. Live JobHandles keep working — they hold the job
        # object, not the index entry.
        self.max_finished_jobs = max(0, int(max_finished_jobs))
        self._clock = clock
        self._cond = threading.Condition()
        self._jobs: Dict[str, CheckJob] = {}
        self._seq = itertools.count()
        self._closing = threading.Event()
        self._active_checker = None
        self._scheduler = threading.Thread(
            target=self._run_scheduler, name="check-service", daemon=True
        )
        self._scheduler.start()

    # -- submission ---------------------------------------------------------

    def submit(
        self,
        model=None,
        *,
        model_name: Optional[str] = None,
        model_args: Optional[dict] = None,
        options: Optional[dict] = None,
        spawn: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        hbm_budget_mib: Optional[float] = None,
        aot_namespace: Optional[str] = None,
        job_id: Optional[str] = None,
    ) -> JobHandle:
        """Admits one check job; returns immediately with a handle.

        Either ``model_name`` (a zoo entry; ``model_args`` forwarded to
        its factory — this route shares the AOT cache automatically) or
        ``model`` (a ``BatchableModel`` instance or zero-arg factory;
        pass ``aot_namespace=`` yourself iff submissions under that
        namespace are configured identically). ``options`` takes the
        builder knobs (``target_state_count``, ``target_max_depth``,
        ``symmetry``); ``spawn`` any ``spawn_tpu_bfs`` kwarg;
        ``hbm_budget_mib`` the tenant's device budget."""
        if self._closing.is_set():
            raise RuntimeError("CheckService is closed")
        for field_name, value in (
            ("model_args", model_args),
            ("options", options),
            ("spawn", spawn),
        ):
            if value is not None and not isinstance(value, dict):
                raise ValueError(
                    f"{field_name} must be an object/dict, "
                    f"got {type(value).__name__}"
                )
        model_args = dict(model_args or {})
        if model_name is not None:
            if model is not None:
                raise ValueError("pass model or model_name, not both")
            try:
                factory_fn = self.zoo[model_name]
            except KeyError:
                raise ValueError(
                    f"unknown model {model_name!r} "
                    f"(zoo has: {sorted(self.zoo)})"
                ) from None
            def factory(fn=factory_fn, kw=model_args):
                return fn(**kw)
            if aot_namespace is None:
                # Canonicalize zoo aliases ("2pc"/"two_phase_commit" map
                # to one factory): namespace on the factory's first zoo
                # name, so aliases share the executable cache instead of
                # recompiling per spelling.
                canonical = min(
                    k for k, v in self.zoo.items() if v is factory_fn
                )
                aot_namespace = zoo_namespace(canonical, model_args)
        elif model is not None:
            if callable(model) and not hasattr(model, "packed_init_states"):
                factory = model
            else:
                def factory(m=model):
                    return m
        else:
            raise ValueError("one of model / model_name is required")
        bad = set(options or {}) - set(_BUILDER_OPTIONS)
        if bad:
            raise ValueError(
                f"unknown options {sorted(bad)} "
                f"(supported: {list(_BUILDER_OPTIONS)})"
            )
        # Coerce the scheduling inputs HERE, not in the scheduler: a
        # non-numeric deadline from an HTTP body reaching sort_key()
        # would kill the scheduler thread and hang every job.
        try:
            priority = int(priority)
            deadline_s = None if deadline_s is None else float(deadline_s)
            hbm_budget_mib = (
                None if hbm_budget_mib is None else float(hbm_budget_mib)
            )
        except (TypeError, ValueError) as e:
            raise ValueError(
                "priority must be an int; deadline_s / hbm_budget_mib "
                f"must be numbers or null ({e})"
            ) from None
        if hbm_budget_mib is None:
            hbm_budget_mib = self.default_hbm_budget_mib
        with self._cond:
            seq = next(self._seq)
            # Default ids draw from the PROCESS-global sequence, not the
            # per-service one: the id doubles as the run_id keying the
            # process-global metrics registries, so two services in one
            # process (common in tests, possible in embedders) must
            # never mint the same "job-0" and merge two jobs' counters.
            jid = job_id or f"job-{next(_GLOBAL_JOB_SEQ)}"
            if jid in self._jobs:
                raise ValueError(f"duplicate job_id {jid!r}")
            job = CheckJob(
                jid,
                factory,
                model_name=model_name,
                options=options,
                spawn=spawn,
                priority=priority,
                deadline_s=deadline_s,
                tenant=tenant,
                hbm_budget_mib=hbm_budget_mib,
                aot_namespace=aot_namespace,
                seq=seq,
                clock=self._clock,
            )
            self._jobs[jid] = job
            self._cond.notify_all()
        return JobHandle(job, self)

    # -- introspection ------------------------------------------------------

    def job(self, job_id: str) -> Optional[CheckJob]:
        with self._cond:
            return self._jobs.get(job_id)

    def jobs(self) -> List[CheckJob]:
        with self._cond:
            return sorted(self._jobs.values(), key=lambda j: j.seq)

    def status(self) -> dict:
        js = self.jobs()
        return {
            "quantum_s": self.quantum_s,
            "closing": self._closing.is_set(),
            "jobs": [j.status() for j in js],
            "counts": {
                state: sum(1 for j in js if j.state == state)
                for state in (
                    JOB_QUEUED, JOB_RUNNING, JOB_SUSPENDED,
                    JOB_DONE, JOB_FAILED, JOB_CANCELLED,
                )
            },
        }

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    # -- the scheduler loop -------------------------------------------------

    def _pick(self) -> Optional[CheckJob]:
        """Highest-priority runnable job (the admission order
        ``CheckJob.sort_key``); reaps cancelled queued jobs in passing.
        Caller holds the condition lock."""
        best = None
        for job in self._jobs.values():
            if not job.runnable():
                continue
            if job.cancel_event.is_set():
                job.payload = None
                job.finish(JOB_CANCELLED)
                continue
            if best is None or job.sort_key() < best.sort_key():
                best = job
        return best

    def _should_preempt_for_peer(self, current: CheckJob) -> bool:
        """Whether suspending the current job at quantum expiry would
        actually hand the device to someone else: some other runnable
        job must sort AHEAD of where the current job would re-enter the
        queue (its round-robin clock stamped to "just ran"). Comparing
        the real sort keys — not just priority — keeps EDF jobs honest
        too: a finite-deadline job sorts first within its class
        regardless of recency, so a priority-only guard would preempt
        it every quantum only to re-pick it (pure checkpoint/restore
        churn) while its peers starve behind the respawn overhead."""
        current_key = current.sort_key(last_run_override=self._clock())
        with self._cond:
            return any(
                j is not current
                and j.runnable()
                and not j.cancel_event.is_set()
                and j.sort_key() < current_key
                for j in self._jobs.values()
            )

    def _run_scheduler(self) -> None:
        while True:
            with self._cond:
                job = self._pick()
                while job is None and not self._closing.is_set():
                    self._cond.wait(timeout=0.5)
                    job = self._pick()
                if self._closing.is_set():
                    return
            try:
                self._run_slice(job)
            except Exception as e:  # noqa: BLE001 - a job must not kill the loop
                job.fail(repr(e))
            self._evict_finished()

    def _spawn(self, job: CheckJob):
        model = job.model_factory()
        builder = model.checker()
        opts = job.options
        if opts.get("target_state_count"):
            builder = builder.target_state_count(opts["target_state_count"])
        if opts.get("target_max_depth"):
            builder = builder.target_max_depth(opts["target_max_depth"])
        if opts.get("symmetry"):
            builder = builder.symmetry()
        spawn = dict(self.default_spawn)
        spawn.update(job.spawn)
        spawn["run_id"] = job.run_id
        # Cross-job executable sharing is a single-device-checker
        # feature for now (the sharded checker has no aot_cache knob);
        # passing it unconditionally would TypeError every job under
        # spawn_method="spawn_sharded_tpu_bfs".
        if (
            job.aot_namespace is not None
            and self.spawn_method == "spawn_tpu_bfs"
        ):
            spawn.setdefault("aot_cache", job.aot_namespace)
        if job.hbm_budget_mib is not None:
            spawn.setdefault("hbm_budget_mib", job.hbm_budget_mib)
        if job.payload is not None:
            spawn["resume_from"] = job.payload
            job.payload = None
        return getattr(builder, self.spawn_method)(**spawn)

    def _poll_discoveries(self, job: CheckJob, checker) -> None:
        try:
            names = set(checker._discovery_names())
        except Exception:  # noqa: BLE001 - mid-run best effort
            return
        fresh = names - job.seen_discoveries
        if fresh:
            job.seen_discoveries |= names
            if job.first_discovery_t is None:
                job.first_discovery_t = self._clock()

    def _run_slice(self, job: CheckJob) -> None:
        """One scheduling slice: (re)spawn the job's checker, let it run
        for up to a quantum (to completion when nothing else wants the
        device), then preempt/harvest. Strictly serialized — the device
        has exactly one claimant at any time."""
        job.state = JOB_RUNNING
        job.slices += 1
        t0 = self._clock()
        if job.started_t is None:
            job.started_t = t0
        try:
            checker = self._spawn(job)
        except Exception as e:  # noqa: BLE001 - bad knobs/model = job failure
            job.fail(repr(e))
            return
        self._active_checker = checker
        # On resume, the restored discoveries must not count as "first".
        self._poll_discoveries(job, checker)
        slice_end = t0 + self.quantum_s

        # A backend without preemption support (host engines raise
        # NotImplementedError from the base request_preempt) degrades
        # gracefully: its slice simply runs to completion — failing the
        # job while its worker threads live on would leave TWO checkers
        # claiming the device once the scheduler moved on.
        def try_preempt() -> bool:
            try:
                checker.request_preempt()
                return True
            except NotImplementedError:
                return False

        preempting = False
        preemptible = True
        try:
            while not checker.is_done():
                if (job.cancel_event.is_set() or self._closing.is_set()) \
                        and not preempting and preemptible:
                    preemptible = preempting = try_preempt()
                elif (
                    not preempting
                    and preemptible
                    and self._clock() >= slice_end
                    and self._should_preempt_for_peer(job)
                ):
                    preemptible = preempting = try_preempt()
                self._poll_discoveries(job, checker)
                time.sleep(self.poll_interval_s)
            for h in checker.handles():
                h.join()
            self._poll_discoveries(job, checker)
        finally:
            self._active_checker = None
            job.active_s += self._clock() - t0
            job.last_run_t = self._clock()
            job.warmup_s += getattr(checker, "warmup_seconds", None) or 0.0
        err = checker.worker_error()
        if err is not None:
            job.fail(repr(err))
            return
        if job.cancel_event.is_set():
            job.finish(JOB_CANCELLED)
            return
        if checker.preempted:
            job.suspend(checker.preempt_payload())
            return
        job.complete(self._finalize(job, checker))

    def _evict_finished(self) -> None:
        """Drops the oldest terminal jobs (and their run registries)
        past the retention cap. Suspended/queued/running jobs are never
        evicted."""
        from ..telemetry import discard_run_registry

        with self._cond:
            finished = sorted(
                (
                    j
                    for j in self._jobs.values()
                    if j.state in (JOB_DONE, JOB_FAILED, JOB_CANCELLED)
                ),
                key=lambda j: j.finished_t or 0.0,
            )
            excess = finished[: max(0, len(finished) - self.max_finished_jobs)]
            for j in excess:
                del self._jobs[j.job_id]
        for j in excess:
            discard_run_registry(j.run_id)

    def _finalize(self, job: CheckJob, checker) -> dict:
        """The completed job's verdict record (the bench's per-job row)."""
        unique = checker.unique_state_count()
        discoveries = {}
        try:
            for name, path in checker.discoveries().items():
                discoveries[name] = {
                    "classification": checker.discovery_classification(name),
                    "length": len(path),
                }
        except Exception as e:  # noqa: BLE001 - verdicts above all
            discoveries = {"error": repr(e)}
        try:
            checker.assert_properties()
            properties_hold = True
        except AssertionError:
            properties_hold = False
        out = io.StringIO()
        try:
            checker.report(WriteReporter(out))
        except Exception:  # noqa: BLE001
            pass
        steady = max(job.active_s - job.warmup_s, 1e-9)
        result = {
            "unique": unique,
            "states": checker.state_count(),
            "max_depth": checker.max_depth(),
            "discoveries": discoveries,
            "properties_hold": properties_hold,
            "report": out.getvalue(),
            "warmup_s": job.warmup_s,
            "rate": unique / steady,
        }
        attribution = checker.attribution_report()
        if attribution is not None:
            result["attribution"] = attribution
            # Compile seconds ACROSS incarnations: the final checker's
            # ledger only covers its own life, but the per-run registry's
            # `*.pipeline.compile_seconds` counters persist through
            # preempt/resume cycles — the honest shared-AOT-cache
            # evidence (a job that compiled in slice 1 and finished in a
            # cache-hitting slice 3 is NOT compile-free).
            try:
                snap = checker.metrics().snapshot()
                result["compile_s_total"] = sum(
                    v
                    for k, v in snap.items()
                    if k.endswith(".pipeline.compile_seconds")
                    and isinstance(v, (int, float))
                )
            except Exception:  # noqa: BLE001 - evidence, not verdict
                pass
        cov = checker.coverage_report()
        if cov is not None:
            result["coverage"] = cov
        return result

    # -- lifecycle ----------------------------------------------------------

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Stops the scheduler: the running slice (if any) is preempted
        at its next wave boundary and left suspended, queued jobs stay
        queued. Idempotent."""
        self._closing.set()
        self._wake()
        self._scheduler.join(timeout=timeout)

    def __enter__(self) -> "CheckService":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
