"""The persistent check scheduler: one device, many jobs, packed waves.

``CheckService`` owns the accelerator the way a database owns its disk: a
scheduler thread admits :class:`CheckJob` s (priority high-first, EDF
within a priority, FIFO within a deadline) and multiplexes the device two
ways:

- **Tenant-packed waves (the default for qualifying jobs).** Same-shape
  jobs — same zoo configuration, no spawn overrides, no symmetry/target
  caps/budget — co-schedule onto ONE physical wave through
  ``checker/packed_tenancy.TenantPackedEngine``: a shared visited table
  under tenant-salted fingerprints, per-lane tenant ids, per-tenant
  result reductions. Concurrency costs ~nothing (BENCH_r12 vs the
  BENCH_r10 time-sliced baseline), admission is "claim a free lane
  slot", late arrivals JOIN the live pack mid-run, and preemption is
  "drop the tenant's lanes" — its survivors hand back as a checkpoint-v2
  payload slice with no device drain. Every packed tenant's verdict is
  bit-identical to its solo run (tests/test_packed_tenancy.py).
- **Wave-granular time-slicing (the fallback).** Non-packable jobs are
  suspended by ``request_preempt()`` (wave state drains to a host-side
  checkpoint payload at the next wave/drain boundary) and resumed later
  with ``resume_from=<payload>`` — bit-identical to an uninterrupted run
  (tests/test_preempt_resume.py). Jobs whose backend cannot preempt at
  all run their slice to completion; that fact is surfaced honestly as
  ``preemptible: false`` in ``status()`` instead of being discovered
  from a swallowed NotImplementedError.

Jobs multiplex onto the shared AOT rung cache (``checker/tpu.py``'s
``shared_aot_cache``): two jobs of the same zoo configuration share every
``(bucket, table_capacity)`` wave/drain executable (the packed engine
shares its wave/seed/rehash executables the same way), so the second job
— and every preempted job's next incarnation — records zero compile
phases. Each job runs under its own ``run_id``: its own metrics registry
and run-stamped trace spans, so per-job ``/metrics`` / ``/status`` / SSE
/ attribution / coverage all work, and packed jobs additionally carry
their ``pack.tenant.*`` lane accounting (PR 3-8 + PR 12 plumbing).

Single-device by design: slices (packed or solo) are strictly
serialized, so the device never has two claimants (the same constraint
the bench's sentinel coordination enforces across processes, here
enforced by the scheduler loop within one).
"""

from __future__ import annotations

import io
import itertools
import threading
import time
from typing import Callable, Dict, List, Optional

from ..report import WriteReporter
from .jobs import (
    JOB_CANCELLED,
    JOB_DONE,
    JOB_FAILED,
    JOB_QUEUED,
    JOB_RUNNING,
    JOB_SUSPENDED,
    CheckJob,
    JobHandle,
)
from .zoo import aot_namespace as zoo_namespace
from .zoo import default_zoo

# Builder options POST /jobs and submit(options=...) accept.
_BUILDER_OPTIONS = ("target_state_count", "target_max_depth", "symmetry")

# Spawn kwargs the service defaults for every job: a bounded drain cap is
# what makes preemption latency a few waves instead of a whole drain (the
# same clamp checkpoint durability applies), and modest capacities fit
# many tenants on one device.
_DEFAULT_SPAWN = {
    "frontier_capacity": 1 << 10,
    "table_capacity": 1 << 16,
    "max_drain_waves": 8,
}

# Default job ids are unique across every service in the process (the
# id is also the run_id, which keys process-global registries).
_GLOBAL_JOB_SEQ = itertools.count()

# Spawn methods whose checkers yield resumable preempt payloads
# (``Checker.supports_preempt``). The admission-time guess; corrected
# from the live checker after the first spawn.
_PREEMPTIBLE_SPAWNS = frozenset({"spawn_tpu_bfs", "spawn_sharded_tpu_bfs"})


class CheckService:
    """A long-lived, in-process checking service.

    ::

        svc = CheckService()
        h1 = svc.submit(model_name="2pc", model_args={"rm_count": 5})
        h2 = svc.submit(model_name="abd", priority=1)   # runs first
        print(h1.result()["unique"], h1.status()["latency"]["ttfv_s"])
        svc.close()

    ``quantum_s`` is the scheduling quantum: a running job is preempted
    once its slice exceeds it *and* another job is runnable (a sole job
    runs uninterrupted — preemption exists for sharing, not ceremony).
    ``default_hbm_budget_mib`` is the per-tenant device budget applied to
    jobs that don't set their own (the PR 5 tiered store enforces it).
    """

    def __init__(
        self,
        *,
        quantum_s: float = 1.0,
        poll_interval_s: float = 0.005,
        zoo: Optional[Dict[str, Callable]] = None,
        default_spawn: Optional[dict] = None,
        default_hbm_budget_mib: Optional[float] = None,
        spawn_method: str = "spawn_tpu_bfs",
        max_finished_jobs: int = 256,
        packing: bool = True,
        max_pack_tenants: int = 8,
        pack_async: bool = False,
        clock=time.monotonic,
    ):
        self.quantum_s = float(quantum_s)
        self.poll_interval_s = float(poll_interval_s)
        self.zoo = dict(zoo) if zoo is not None else default_zoo()
        self.default_spawn = dict(_DEFAULT_SPAWN)
        if default_spawn:
            self.default_spawn.update(default_spawn)
        self.default_hbm_budget_mib = default_hbm_budget_mib
        self.spawn_method = spawn_method
        # Tenant-packed waves (checker/packed_tenancy.py): qualifying
        # same-shape jobs share one physical dispatch instead of
        # time-slicing. ``packing=False`` restores the pure time-slicer;
        # ``max_pack_tenants`` is the lane-slot count K;
        # ``pack_async=True`` runs the pack's host half (per-tenant
        # probes, parent logs, survivor re-entry) on a pipeline worker
        # overlapped with the next dispatch.
        self.packing = bool(packing)
        self.max_pack_tenants = max(1, int(max_pack_tenants))
        self.pack_async = bool(pack_async)
        # Zoo-configuration model cache: one model instance per AOT
        # namespace, shared by admission-time budget validation and the
        # packed engines (models are pure packed-array containers).
        self._pack_models: Dict[str, object] = {}
        # Retention: terminal jobs (and their run registries) beyond
        # this count are evicted oldest-first, so a long-lived service
        # does not accrete one registry + result blob per finished job
        # forever. Live JobHandles keep working — they hold the job
        # object, not the index entry.
        self.max_finished_jobs = max(0, int(max_finished_jobs))
        self._clock = clock
        self._cond = threading.Condition()
        self._jobs: Dict[str, CheckJob] = {}
        self._seq = itertools.count()
        self._closing = threading.Event()
        self._active_checker = None
        self._scheduler = threading.Thread(
            target=self._run_scheduler, name="check-service", daemon=True
        )
        self._scheduler.start()

    # -- submission ---------------------------------------------------------

    def submit(
        self,
        model=None,
        *,
        model_name: Optional[str] = None,
        model_args: Optional[dict] = None,
        options: Optional[dict] = None,
        spawn: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        hbm_budget_mib: Optional[float] = None,
        aot_namespace: Optional[str] = None,
        job_id: Optional[str] = None,
    ) -> JobHandle:
        """Admits one check job; returns immediately with a handle.

        Either ``model_name`` (a zoo entry; ``model_args`` forwarded to
        its factory — this route shares the AOT cache automatically) or
        ``model`` (a ``BatchableModel`` instance or zero-arg factory;
        pass ``aot_namespace=`` yourself iff submissions under that
        namespace are configured identically). ``options`` takes the
        builder knobs (``target_state_count``, ``target_max_depth``,
        ``symmetry``); ``spawn`` any ``spawn_tpu_bfs`` kwarg;
        ``hbm_budget_mib`` the tenant's device budget."""
        if self._closing.is_set():
            raise RuntimeError("CheckService is closed")
        for field_name, value in (
            ("model_args", model_args),
            ("options", options),
            ("spawn", spawn),
        ):
            if value is not None and not isinstance(value, dict):
                raise ValueError(
                    f"{field_name} must be an object/dict, "
                    f"got {type(value).__name__}"
                )
        model_args = dict(model_args or {})
        if model_name is not None:
            if model is not None:
                raise ValueError("pass model or model_name, not both")
            try:
                factory_fn = self.zoo[model_name]
            except KeyError:
                raise ValueError(
                    f"unknown model {model_name!r} "
                    f"(zoo has: {sorted(self.zoo)})"
                ) from None
            def factory(fn=factory_fn, kw=model_args):
                return fn(**kw)
            if aot_namespace is None:
                # Canonicalize zoo aliases ("2pc"/"two_phase_commit" map
                # to one factory): namespace on the factory's first zoo
                # name, so aliases share the executable cache instead of
                # recompiling per spelling.
                canonical = min(
                    k for k, v in self.zoo.items() if v is factory_fn
                )
                aot_namespace = zoo_namespace(canonical, model_args)
        elif model is not None:
            if callable(model) and not hasattr(model, "packed_init_states"):
                factory = model
            else:
                def factory(m=model):
                    return m
        else:
            raise ValueError("one of model / model_name is required")
        bad = set(options or {}) - set(_BUILDER_OPTIONS)
        if bad:
            raise ValueError(
                f"unknown options {sorted(bad)} "
                f"(supported: {list(_BUILDER_OPTIONS)})"
            )
        # Coerce the scheduling inputs HERE, not in the scheduler: a
        # non-numeric deadline from an HTTP body reaching sort_key()
        # would kill the scheduler thread and hang every job.
        try:
            priority = int(priority)
            deadline_s = None if deadline_s is None else float(deadline_s)
            hbm_budget_mib = (
                None if hbm_budget_mib is None else float(hbm_budget_mib)
            )
        except (TypeError, ValueError) as e:
            raise ValueError(
                "priority must be an int; deadline_s / hbm_budget_mib "
                f"must be numbers or null ({e})"
            ) from None
        if hbm_budget_mib is None:
            hbm_budget_mib = self.default_hbm_budget_mib
        # Budget-derived table sizing, validated AT ADMISSION: an
        # over-budget request (the budget cannot fit even one worst-case
        # wave of this model at the configured frontier) is rejected
        # here with a clear error, not discovered as an OOM/ValueError
        # on the scheduler thread mid-slice.
        derived_table_capacity = None
        if hbm_budget_mib is not None:
            derived_table_capacity = self._validate_budget(
                factory, aot_namespace, spawn, hbm_budget_mib
            )
        packable, packable_reason = self._classify_packable(
            aot_namespace=aot_namespace,
            options=options,
            spawn=spawn,
            hbm_budget_mib=hbm_budget_mib,
        )
        with self._cond:
            seq = next(self._seq)
            # Default ids draw from the PROCESS-global sequence, not the
            # per-service one: the id doubles as the run_id keying the
            # process-global metrics registries, so two services in one
            # process (common in tests, possible in embedders) must
            # never mint the same "job-0" and merge two jobs' counters.
            jid = job_id or f"job-{next(_GLOBAL_JOB_SEQ)}"
            if jid in self._jobs:
                raise ValueError(f"duplicate job_id {jid!r}")
            job = CheckJob(
                jid,
                factory,
                model_name=model_name,
                options=options,
                spawn=spawn,
                priority=priority,
                deadline_s=deadline_s,
                tenant=tenant,
                hbm_budget_mib=hbm_budget_mib,
                aot_namespace=aot_namespace,
                seq=seq,
                clock=self._clock,
            )
            job.preemptible = self.spawn_method in _PREEMPTIBLE_SPAWNS
            job.packable = packable
            job.packable_reason = packable_reason
            job.derived_table_capacity = derived_table_capacity
            self._jobs[jid] = job
            self._cond.notify_all()
        return JobHandle(job, self)

    # -- admission policy ---------------------------------------------------

    # Model-cache cap: a long-lived service fed many distinct zoo
    # configurations must not pin a packed-array model instance per
    # namespace forever (same retention rule as max_finished_jobs).
    _PACK_MODEL_CACHE_MAX = 32

    def _model_for(self, factory: Callable, aot_namespace: Optional[str]):
        """The job's model instance — cached per AOT namespace (the
        namespace asserts identical configuration, so one instance
        serves budget validation and every pack under that key);
        oldest-inserted entries evict past the cap."""
        if aot_namespace is None:
            return factory()
        model = self._pack_models.get(aot_namespace)
        if model is None:
            model = factory()
            self._pack_models[aot_namespace] = model
            while len(self._pack_models) > self._PACK_MODEL_CACHE_MAX:
                self._pack_models.pop(next(iter(self._pack_models)))
        return model

    def _validate_budget(
        self, factory, aot_namespace, spawn, hbm_budget_mib
    ) -> int:
        """Derives the tenant's device table capacity from its
        ``hbm_budget_mib`` (the budget IS the tenant's paid allocation —
        the fixed ``_DEFAULT_SPAWN`` constant both over-allocated poor
        tenants and growth-churned rich ones) and rejects inadmissible
        budgets up front. Returns the capacity in rows."""
        from ..checker.tpu import min_admissible_hbm_budget_mib
        from ..storage import max_table_rows_for_budget

        frontier = (spawn or {}).get(
            "frontier_capacity",
            self.default_spawn.get("frontier_capacity", 1 << 10),
        )
        model = self._model_for(factory, aot_namespace)
        min_budget = min_admissible_hbm_budget_mib(model, frontier)
        if hbm_budget_mib < min_budget:
            raise ValueError(
                f"hbm_budget_mib={hbm_budget_mib} rejected at admission: "
                f"one worst-case wave at frontier_capacity={frontier} "
                f"needs at least {min_budget:.3f} MiB for this model; "
                "raise the budget or shrink frontier_capacity"
            )
        return max_table_rows_for_budget(hbm_budget_mib)

    # default_spawn keys the packed engine either honors directly
    # (frontier/table shape, async pipelining) or that cannot change
    # packed semantics (max_drain_waves bounds SOLO preemption latency —
    # the engine is wave-granular by construction; aot_cache names the
    # SOLO executable namespace — packs use their own "pack:" one). Any
    # other service-wide default (budgets, expand_fps, hashset_impl,
    # checkpointing, ...) would be silently dropped by packing, so its
    # presence honestly disqualifies packing instead.
    _PACK_SAFE_DEFAULT_SPAWN = frozenset({
        "frontier_capacity",
        "table_capacity",
        "max_drain_waves",
        "aot_cache",
        "async_pipeline",
    })

    def _classify_packable(self, *, aot_namespace, options, spawn,
                           hbm_budget_mib):
        """Whether a submission qualifies for tenant-packed waves, and
        the honest reason when it does not (surfaced via ``status()`` so
        operators can see which jobs serialize the device)."""
        if not self.packing:
            return False, "packing disabled on this service"
        if self.spawn_method != "spawn_tpu_bfs":
            return False, f"spawn_method {self.spawn_method!r}"
        if aot_namespace is None:
            return False, "custom model (no AOT namespace to pack under)"
        if spawn:
            return False, f"spawn overrides {sorted(spawn)}"
        unsafe = set(self.default_spawn) - self._PACK_SAFE_DEFAULT_SPAWN
        if unsafe:
            return False, (
                f"service default_spawn overrides {sorted(unsafe)} "
                "(the packed engine cannot honor them)"
            )
        opts = options or {}
        if opts.get("symmetry"):
            return False, "symmetry reduction (orbit keys cannot salt)"
        if opts.get("target_state_count"):
            return False, "target_state_count (per-wave overshoot cap)"
        if hbm_budget_mib is not None:
            return False, "hbm_budget_mib (solo tiered run)"
        return True, None

    # -- introspection ------------------------------------------------------

    def job(self, job_id: str) -> Optional[CheckJob]:
        with self._cond:
            return self._jobs.get(job_id)

    def jobs(self) -> List[CheckJob]:
        with self._cond:
            return sorted(self._jobs.values(), key=lambda j: j.seq)

    def status(self) -> dict:
        js = self.jobs()
        return {
            "quantum_s": self.quantum_s,
            "closing": self._closing.is_set(),
            "jobs": [j.status() for j in js],
            "counts": {
                state: sum(1 for j in js if j.state == state)
                for state in (
                    JOB_QUEUED, JOB_RUNNING, JOB_SUSPENDED,
                    JOB_DONE, JOB_FAILED, JOB_CANCELLED,
                )
            },
        }

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    # -- the scheduler loop -------------------------------------------------

    def _pick(self) -> Optional[CheckJob]:
        """Highest-priority runnable job (the admission order
        ``CheckJob.sort_key``); reaps cancelled queued jobs in passing.
        Caller holds the condition lock."""
        best = None
        for job in self._jobs.values():
            if not job.runnable():
                continue
            if job.cancel_event.is_set():
                job.payload = None
                job.finish(JOB_CANCELLED)
                continue
            if best is None or job.sort_key() < best.sort_key():
                best = job
        return best

    def _should_preempt_for_peer(self, current: CheckJob) -> bool:
        """Whether suspending the current job at quantum expiry would
        actually hand the device to someone else: some other runnable
        job must sort AHEAD of where the current job would re-enter the
        queue (its round-robin clock stamped to "just ran"). Comparing
        the real sort keys — not just priority — keeps EDF jobs honest
        too: a finite-deadline job sorts first within its class
        regardless of recency, so a priority-only guard would preempt
        it every quantum only to re-pick it (pure checkpoint/restore
        churn) while its peers starve behind the respawn overhead."""
        current_key = current.sort_key(last_run_override=self._clock())
        with self._cond:
            return any(
                j is not current
                and j.runnable()
                and not j.cancel_event.is_set()
                and j.sort_key() < current_key
                for j in self._jobs.values()
            )

    def _run_scheduler(self) -> None:
        while True:
            with self._cond:
                job = self._pick()
                while job is None and not self._closing.is_set():
                    self._cond.wait(timeout=0.5)
                    job = self._pick()
                if self._closing.is_set():
                    return
            try:
                if self.packing and job.packable:
                    self._run_packed_slice(job)
                else:
                    self._run_slice(job)
            except Exception as e:  # noqa: BLE001 - a job must not kill the loop
                job.fail(repr(e))
            self._evict_finished()

    def _spawn(self, job: CheckJob):
        model = job.model_factory()
        builder = model.checker()
        opts = job.options
        if opts.get("target_state_count"):
            builder = builder.target_state_count(opts["target_state_count"])
        if opts.get("target_max_depth"):
            builder = builder.target_max_depth(opts["target_max_depth"])
        if opts.get("symmetry"):
            builder = builder.symmetry()
        spawn = dict(self.default_spawn)
        spawn.update(job.spawn)
        if (
            job.derived_table_capacity is not None
            and "table_capacity" not in job.spawn
        ):
            # The tenant's budget, not the fixed default, sizes its
            # device table (see _validate_budget).
            spawn["table_capacity"] = job.derived_table_capacity
        spawn["run_id"] = job.run_id
        # Cross-job executable sharing is a single-device-checker
        # feature for now (the sharded checker has no aot_cache knob);
        # passing it unconditionally would TypeError every job under
        # spawn_method="spawn_sharded_tpu_bfs".
        if (
            job.aot_namespace is not None
            and self.spawn_method == "spawn_tpu_bfs"
        ):
            spawn.setdefault("aot_cache", job.aot_namespace)
        if job.hbm_budget_mib is not None:
            spawn.setdefault("hbm_budget_mib", job.hbm_budget_mib)
        if job.payload is not None:
            spawn["resume_from"] = job.payload
            job.payload = None
        method = getattr(builder, self.spawn_method)
        import inspect

        sig = inspect.signature(method)
        if not any(
            p.kind is p.VAR_KEYWORD for p in sig.parameters.values()
        ):
            # Host-engine spawn methods (spawn_bfs/dfs/...) take no
            # kwargs: drop the device-spawn defaults (run_id included —
            # their metrics land in the default registry) so the
            # degrade-gracefully branch below is actually reachable
            # instead of dying on a TypeError at spawn.
            spawn = {k: v for k, v in spawn.items() if k in sig.parameters}
        return method(**spawn)

    def _poll_discoveries(self, job: CheckJob, checker) -> None:
        try:
            names = set(checker._discovery_names())
        except Exception:  # noqa: BLE001 - mid-run best effort
            return
        fresh = names - job.seen_discoveries
        if fresh:
            job.seen_discoveries |= names
            if job.first_discovery_t is None:
                job.first_discovery_t = self._clock()

    def _run_slice(self, job: CheckJob) -> None:
        """One scheduling slice: (re)spawn the job's checker, let it run
        for up to a quantum (to completion when nothing else wants the
        device), then preempt/harvest. Strictly serialized — the device
        has exactly one claimant at any time."""
        job.state = JOB_RUNNING
        job.slices += 1
        t0 = self._clock()
        if job.started_t is None:
            job.started_t = t0
        try:
            checker = self._spawn(job)
        except Exception as e:  # noqa: BLE001 - bad knobs/model = job failure
            job.fail(repr(e))
            return
        self._active_checker = checker
        # Honest preemptibility: the admission-time guess (spawn-method
        # map) corrected from the live checker's own declaration.
        job.preemptible = bool(getattr(checker, "supports_preempt", False))
        # On resume, the restored discoveries must not count as "first".
        self._poll_discoveries(job, checker)
        slice_end = t0 + self.quantum_s

        # A backend without preemption support (host engines raise
        # NotImplementedError from the base request_preempt) degrades
        # gracefully: its slice simply runs to completion — failing the
        # job while its worker threads live on would leave TWO checkers
        # claiming the device once the scheduler moved on.
        def try_preempt() -> bool:
            try:
                checker.request_preempt()
                return True
            except NotImplementedError:
                job.preemptible = False
                return False

        preempting = False
        preemptible = True
        try:
            while not checker.is_done():
                if (job.cancel_event.is_set() or self._closing.is_set()) \
                        and not preempting and preemptible:
                    preemptible = preempting = try_preempt()
                elif (
                    not preempting
                    and preemptible
                    and self._clock() >= slice_end
                    and self._should_preempt_for_peer(job)
                ):
                    preemptible = preempting = try_preempt()
                self._poll_discoveries(job, checker)
                time.sleep(self.poll_interval_s)
            for h in checker.handles():
                h.join()
            self._poll_discoveries(job, checker)
        finally:
            self._active_checker = None
            job.active_s += self._clock() - t0
            job.last_run_t = self._clock()
            job.warmup_s += getattr(checker, "warmup_seconds", None) or 0.0
        err = checker.worker_error()
        if err is not None:
            job.fail(repr(err))
            return
        if job.cancel_event.is_set():
            job.finish(JOB_CANCELLED)
            return
        if checker.preempted:
            job.suspend(checker.preempt_payload())
            return
        job.complete(self._finalize(job, checker))

    # -- the packer (tenant-packed waves) -----------------------------------

    def _pack_peers(self, key: str, members: Dict[str, CheckJob]):
        """Runnable packable same-configuration jobs not yet in the pack
        — the admission candidates, best-first."""
        with self._cond:
            peers = [
                j
                for j in self._jobs.values()
                if j.job_id not in members
                and j.runnable()
                and not j.cancel_event.is_set()
                and j.packable
                and j.aot_namespace == key
            ]
        return sorted(peers, key=lambda j: j.sort_key())

    def _pack_contender(self, key: str, members: Dict[str, CheckJob],
                        can_join: bool) -> bool:
        """Whether a runnable job OUTSIDE the pack — one that cannot
        simply join it — sorts ahead of where the pack's best member
        would re-enter the queue. Same honesty rule as
        ``_should_preempt_for_peer``: suspending the pack must actually
        hand the device to someone else. A same-shape packable job
        counts as a contender too once the pack has no free lane
        (``can_join=False``) — otherwise a full pack would starve a
        higher-priority same-shape arrival that the time-slicer would
        have preempted for."""
        now = self._clock()
        reentry = min(
            j.sort_key(last_run_override=now) for j in members.values()
        )
        with self._cond:
            return any(
                j.job_id not in members
                and j.runnable()
                and not j.cancel_event.is_set()
                and not (
                    can_join and j.packable and j.aot_namespace == key
                )
                and j.sort_key() < reentry
                for j in self._jobs.values()
            )

    def _pack_admit(self, engine, job: CheckJob):
        """Claims a lane slot for one job (restoring its suspended
        payload slice, if any); stamps the membership clocks only AFTER
        the admission succeeds — a failed admit must not leave the job
        reporting packed:true with a counted slice."""
        view = engine.admit(
            job.job_id,
            job.run_id,
            depth_cap=job.options.get("target_max_depth"),
            resume_from=job.payload,
        )
        job.payload = None
        job.state = JOB_RUNNING
        job.slices += 1
        job.packed = True
        now = self._clock()
        if job.started_t is None:
            job.started_t = now
        job.pack_join_t = now
        # Restored discoveries must not count as "first" for ttfv.
        try:
            job.seen_discoveries |= set(view._discovery_names())
        except Exception:  # noqa: BLE001 - best effort
            pass
        return view

    def _try_pack_admit(self, engine, job, members, views) -> bool:
        try:
            view = self._pack_admit(engine, job)
        except Exception as e:  # noqa: BLE001 - bad knobs = job failure
            job.fail(repr(e))
            return False
        members[job.job_id] = job
        views[job.job_id] = view
        return True

    def _pack_leave(self, job: CheckJob, view) -> None:
        """Membership clocks on any exit (complete/suspend/cancel)."""
        now = self._clock()
        job.active_s += now - (job.pack_join_t or now)
        job.pack_join_t = None
        job.last_run_t = now
        job.warmup_s += getattr(view, "warmup_seconds", None) or 0.0

    def _suspend_pack(self, engine, members, views) -> None:
        """Drops every member's lanes (no device drain): each hands back
        its survivors as a checkpoint-v2 payload slice and re-enters the
        admission queue suspended."""
        for jid, job in list(members.items()):
            # A cancelled member's payload would be thrown away —
            # discard up front instead of building the full parent-map
            # export on the scheduler thread.
            cancelled = job.cancel_event.is_set()
            payload = engine.drop(jid, discard=cancelled)
            self._pack_leave(job, views[jid])
            if cancelled:
                job.payload = None
                job.finish(JOB_CANCELLED)
            else:
                job.suspend(payload)
        members.clear()
        views.clear()

    def _run_packed_slice(self, lead: CheckJob) -> None:
        """One packed slice: every runnable same-configuration packable
        job co-schedules onto one ``TenantPackedEngine`` — shared waves,
        per-tenant lane accounting. Late same-shape arrivals JOIN the
        live pack (admission = claim a free lane slot); a member's
        cancel drops only its lanes; quantum expiry suspends the pack
        only when an outside contender would actually be picked.
        Strictly serialized with every other slice — the device still
        has exactly one claimant."""
        from ..checker.packed_tenancy import TenantPackedEngine

        key = lead.aot_namespace
        spawn = dict(self.default_spawn)
        model = self._model_for(lead.model_factory, key)
        founders = [lead, *self._pack_peers(key, {})]
        base_table = spawn.get("table_capacity", 1 << 16)
        # Size the shared table for the founding fleet up front: K
        # tenants' visited sets share one table, and pre-sizing avoids
        # the growth rehashes (and their per-shape compiles) a
        # per-tenant-sized table would churn through mid-pack.
        m = 1
        while m < min(len(founders), self.max_pack_tenants):
            m *= 2
        engine = TenantPackedEngine(
            model,
            frontier_capacity=spawn.get("frontier_capacity", 1 << 10),
            table_capacity=base_table * m,
            max_tenants=self.max_pack_tenants,
            # Packed waves are occupancy-dense by construction (that is
            # the point of packing) — the bucket ladder would only buy
            # a compile shape per rung for the few ramp-up waves.
            bucket_ladder=0,
            aot_cache=f"pack:{key}",
            resume_capacity=base_table,
            # The service knob, or a service-wide async default (a
            # pack-safe default_spawn key) — either opts the pack's
            # host half onto the pipeline worker.
            async_pipeline=(
                self.pack_async
                or bool(spawn.get("async_pipeline"))
            ),
        )
        members: Dict[str, CheckJob] = {}
        views: Dict[str, object] = {}
        self._active_checker = engine
        slice_end = self._clock() + self.quantum_s
        try:
            for job in founders:
                if engine.free_slots() == 0:
                    break
                if job.job_id not in members:
                    self._try_pack_admit(engine, job, members, views)
            while members and engine.live_count():
                if self._closing.is_set():
                    self._suspend_pack(engine, members, views)
                    return
                for jid, job in list(members.items()):
                    if job.cancel_event.is_set():
                        engine.drop(jid, discard=True)
                        self._pack_leave(job, views.pop(jid))
                        members.pop(jid)
                        job.payload = None
                        job.finish(JOB_CANCELLED)
                if not members:
                    return
                if engine.free_slots():
                    for job in self._pack_peers(key, members):
                        if engine.free_slots() == 0:
                            break
                        self._try_pack_admit(engine, job, members, views)
                if (
                    self._clock() >= slice_end
                    and self._pack_contender(
                        key, members, engine.free_slots() > 0
                    )
                ):
                    self._suspend_pack(engine, members, views)
                    return
                for done_key in engine.step():
                    job = members.pop(done_key)
                    view = views.pop(done_key)
                    # Final discovery sweep BEFORE completing: a
                    # discovery landing in the job's last wave must
                    # still stamp first_discovery_t (ttfv) — the solo
                    # path polls once more after join for the same
                    # reason.
                    self._poll_discoveries(job, view)
                    self._pack_leave(job, view)
                    engine.release(done_key)
                    job.complete(self._finalize(job, view))
                for jid, job in members.items():
                    self._poll_discoveries(job, views[jid])
        except Exception as e:  # noqa: BLE001 - engine failure fails members
            if not members:
                raise
            err = repr(e)
            for job in members.values():
                job.fail(err)
        finally:
            self._active_checker = None
            engine.close()

    def _evict_finished(self) -> None:
        """Drops the oldest terminal jobs (and their run registries)
        past the retention cap. Suspended/queued/running jobs are never
        evicted."""
        from ..telemetry import discard_run_registry

        with self._cond:
            finished = sorted(
                (
                    j
                    for j in self._jobs.values()
                    if j.state in (JOB_DONE, JOB_FAILED, JOB_CANCELLED)
                ),
                key=lambda j: j.finished_t or 0.0,
            )
            excess = finished[: max(0, len(finished) - self.max_finished_jobs)]
            for j in excess:
                del self._jobs[j.job_id]
        for j in excess:
            discard_run_registry(j.run_id)

    def _finalize(self, job: CheckJob, checker) -> dict:
        """The completed job's verdict record (the bench's per-job row)."""
        unique = checker.unique_state_count()
        discoveries = {}
        try:
            for name, path in checker.discoveries().items():
                discoveries[name] = {
                    "classification": checker.discovery_classification(name),
                    "length": len(path),
                }
        except Exception as e:  # noqa: BLE001 - verdicts above all
            discoveries = {"error": repr(e)}
        try:
            checker.assert_properties()
            properties_hold = True
        except AssertionError:
            properties_hold = False
        out = io.StringIO()
        try:
            checker.report(WriteReporter(out))
        except Exception:  # noqa: BLE001
            pass
        steady = max(job.active_s - job.warmup_s, 1e-9)
        result = {
            "unique": unique,
            "states": checker.state_count(),
            "max_depth": checker.max_depth(),
            "discoveries": discoveries,
            "properties_hold": properties_hold,
            "report": out.getvalue(),
            "warmup_s": job.warmup_s,
            "rate": unique / steady,
        }
        attribution = checker.attribution_report()
        if attribution is not None:
            result["attribution"] = attribution
            # Compile seconds ACROSS incarnations: the final checker's
            # ledger only covers its own life, but the per-run registry's
            # `*.pipeline.compile_seconds` counters persist through
            # preempt/resume cycles — the honest shared-AOT-cache
            # evidence (a job that compiled in slice 1 and finished in a
            # cache-hitting slice 3 is NOT compile-free).
            try:
                snap = checker.metrics().snapshot()
                result["compile_s_total"] = sum(
                    v
                    for k, v in snap.items()
                    if k.endswith(".pipeline.compile_seconds")
                    and isinstance(v, (int, float))
                )
            except Exception:  # noqa: BLE001 - evidence, not verdict
                pass
        cov = checker.coverage_report()
        if cov is not None:
            result["coverage"] = cov
        return result

    # -- lifecycle ----------------------------------------------------------

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Stops the scheduler: the running slice (if any) is preempted
        at its next wave boundary and left suspended, queued jobs stay
        queued. Idempotent."""
        self._closing.set()
        self._wake()
        self._scheduler.join(timeout=timeout)

    def __enter__(self) -> "CheckService":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
