"""The registered model zoo: the names ``POST /jobs`` accepts.

Each entry is a factory taking JSON-friendly kwargs and returning a
``BatchableModel``. The zoo doubles as the AOT-cache namespace source —
two jobs submitting the same zoo name with the same args share the
process-global wave/drain executables (``checker/tpu.py``'s
``shared_aot_cache``), which is what makes a resident service cheap:
same-shaped waves across tenants never recompile.
"""

from __future__ import annotations

from typing import Callable, Dict


def _two_phase(rm_count=5, **kw):
    from ..models.two_phase_commit import TwoPhaseSys

    return TwoPhaseSys(int(rm_count), **kw)


def _abd(clients=2, servers=2, ordered=False, **kw):
    from ..models.linearizable_register import AbdModelCfg

    if ordered:
        from ..actor import Network

        kw.setdefault("network", Network.new_ordered())
    return AbdModelCfg(int(clients), int(servers), **kw).into_model()


def _paxos(clients=2, servers=3, **kw):
    from ..models.paxos import PaxosModelCfg

    return PaxosModelCfg(int(clients), int(servers), **kw).into_model()


def _increment_lock(threads=4, **kw):
    from ..models.increment import IncrementLock

    return IncrementLock(int(threads), **kw)


def _raft(server_count=5, max_term=1, lossy=True, retain=None, **kw):
    from ..models.raft import RaftModelCfg

    model = RaftModelCfg(
        server_count=int(server_count), max_term=int(max_term),
        lossy=bool(lossy), **kw
    ).into_model()
    if retain:
        model = model.retain_properties(
            *(retain if isinstance(retain, (list, tuple)) else [retain])
        )
    return model


def _single_copy(clients=4, servers=1, **kw):
    from ..models.single_copy_register import SingleCopyModelCfg

    return SingleCopyModelCfg(int(clients), int(servers), **kw).into_model()


def _sharded_kv(shards=2, keys=2, max_version=1, guarded=False, **kw):
    from ..models.sharded_kv import ShardedKv

    return ShardedKv(
        int(shards), int(keys), int(max_version), guarded=bool(guarded),
        **kw,
    )


def default_zoo() -> Dict[str, Callable]:
    """Name -> model factory for the HTTP front-end (the bench legs'
    model set). Import-light: factories import their model lazily."""
    return {
        "2pc": _two_phase,
        "two_phase_commit": _two_phase,
        "abd": _abd,
        "linearizable_register": _abd,
        "paxos": _paxos,
        "increment_lock": _increment_lock,
        "raft": _raft,
        "single_copy_register": _single_copy,
        # ROADMAP 6(b) zoo growth: the too-big-to-enumerate swarm
        # workload (S=4, keys=8 is ~10^14 states; the default config is
        # the exhaustively-checkable parity size).
        "sharded_kv": _sharded_kv,
    }


def warm_shapes():
    """The ``(name, args)`` pairs a ``warm_pool=True`` service
    pre-compiles at start: the default configurations of the zoo's
    small always-checkable workloads. Kept deliberately short — each
    shape costs one depth-2 background job at service start (compile
    time when the disk AOT store is cold, milliseconds when warm)."""
    return [
        ("2pc", {}),
        ("abd", {}),
        ("increment_lock", {}),
    ]


def aot_namespace(model_name: str, model_args: dict) -> str:
    """Deterministic AOT-cache namespace for one zoo configuration: the
    name plus the sorted args. Jobs sharing it assert their models are
    configured identically, which the zoo guarantees — same factory,
    same args."""
    args = ",".join(f"{k}={model_args[k]!r}" for k in sorted(model_args))
    return f"zoo:{model_name}({args})"
