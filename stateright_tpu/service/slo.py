"""Service SLO ledger: end-to-end latency attribution per verification
job (ISSUE 18 tentpole b).

Every terminal job already carries its lifecycle stamps (``CheckJob``:
``submitted_t`` → ``started_t`` → summed compile ``warmup_s`` → first
discovery → ``finished_t``) — the ledger folds them into rolling
per-mode latency objectives:

- **ttfv decomposition**: ``queue_s`` (submit → first schedule, the
  admission/scheduler wait), ``compile_s`` (the job's summed compile
  warmup, PR 7's attribution compile phase), ``explore_s`` (the
  residual: device waves + host folds until the first discovery). The
  three are clamped to partition ``ttfv_s`` exactly, so "what do I buy
  by fixing cold-compile" is one subtraction per mode.
- **rolling percentiles**: p50/p99 ttfv and verdict (submit → terminal)
  latency over a bounded window per mode (``exhaustive`` / ``swarm`` /
  ``packed`` — a packed slice's mode wins over its base mode), plus
  registry histograms for the full distributions.
- **SLO targets + burn rate**: configurable targets
  (``CheckService(slo_targets={"ttfv_s": 30, "verdict_s": 120,
  "objective": 0.99})``); the burn-rate gauge is the windowed violation
  rate over the error budget ``1 - objective`` (1.0 = burning exactly
  the budget, >1 = on track to miss the SLO).

Surfaces: ``GET /slo`` (service/http.py), the ``slo.*`` metric family
in the default registry (scraped by ``/metrics``, linted by
``registry_hygiene_problems``), ``scripts/slo_report.py`` and the
``service_report.py`` SLO table.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

from ..telemetry.metrics import metrics_registry

MODES = ("exhaustive", "swarm", "packed", "conformance")

# Error-budget objective and latency targets; targets=None keeps the
# ledger observational (percentiles/decomposition, no burn gauges).
DEFAULT_OBJECTIVE = 0.99


def _pct(values, p):
    """Nearest-rank percentile (the bench's convention) — None on empty."""
    if not values:
        return None
    vs = sorted(values)
    k = max(0, min(len(vs) - 1, int(round((p / 100.0) * (len(vs) - 1)))))
    return vs[k]


def decompose_ttfv(ttfv_s: Optional[float], queued_s: float,
                   compile_s: float) -> Optional[Dict[str, float]]:
    """Splits one job's ttfv into queue/compile/explore, clamped so the
    three sum to ``ttfv_s`` exactly (a discovery can land mid-compile on
    a resumed slice; clamping keeps the partition honest rather than
    reporting phases that overlap)."""
    if ttfv_s is None:
        return None
    t = max(0.0, float(ttfv_s))
    q = min(max(0.0, float(queued_s)), t)
    c = min(max(0.0, float(compile_s)), t - q)
    return {
        "ttfv_s": t,
        "queue_s": q,
        "compile_s": c,
        "explore_s": t - q - c,
    }


class SLOLedger:
    """Rolling per-mode SLO accounting over terminal jobs.

    ``observe(job)`` is called once per job at its completion site (the
    solo-slice and packed-slice verdict paths); jobs that fail or are
    cancelled never observe — the SLO measures served verdicts. All
    state is windowed (``window`` jobs per mode) so a long-lived service
    reports current behaviour, not its launch day."""

    def __init__(self, targets: Optional[dict] = None,
                 registry=None, window: int = 512):
        self.targets = dict(targets or {})
        self.objective = float(
            self.targets.pop("objective", DEFAULT_OBJECTIVE)
        )
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"slo objective must be in (0, 1), got {self.objective}"
            )
        for k in self.targets:
            if k not in ("ttfv_s", "verdict_s"):
                raise ValueError(
                    f"unknown slo target {k!r} (expected 'ttfv_s', "
                    f"'verdict_s', 'objective')"
                )
        self.window = max(8, int(window))
        self._lock = threading.Lock()
        self._obs: Dict[str, deque] = {m: deque(maxlen=self.window) for m in MODES}
        self._jobs: Dict[str, int] = {m: 0 for m in MODES}
        reg = registry if registry is not None else metrics_registry()
        self._reg = reg
        self._g: Dict[tuple, object] = {}
        self._h_ttfv = {m: reg.histogram(f"slo.{m}.ttfv_seconds") for m in MODES}
        self._h_verdict = {
            m: reg.histogram(f"slo.{m}.verdict_seconds") for m in MODES
        }

    def _gauge(self, mode: str, name: str):
        g = self._g.get((mode, name))
        if g is None:
            g = self._reg.gauge(f"slo.{mode}.{name}")
            self._g[(mode, name)] = g
        return g

    @staticmethod
    def job_mode(job) -> str:
        return "packed" if getattr(job, "packed", False) else job.mode

    def observe(self, job) -> None:
        """Folds one completed job; cheap (a few floats under one lock +
        gauge stores), called on the slice thread at verdict time."""
        mode = self.job_mode(job)
        if mode not in self._obs:
            return
        lat = job.latency()
        # Compile-share evidence (warm-start plane): ``compile_s`` is
        # the job's summed warmup; ``compile_free`` is derived from the
        # per-job disk-AOT counters — a disk miss is exactly one fresh
        # compile, so zero misses means every executable came from a
        # cache (memory or disk). None when the job had no AOT binding.
        aot = None
        result = getattr(job, "result", None)
        if isinstance(result, dict):
            aot = result.get("aot")
        compile_free = None
        if aot is not None:
            compile_free = aot.get("aot_cache.disk_miss", 0) == 0
        row = {
            "job_id": job.job_id,
            "verdict_s": lat["wall_s"],
            "queued_s": lat["queued_s"],
            "compile_s": float(job.warmup_s),
            "compile_free": compile_free,
            "warm_start": bool(getattr(job, "warm_start", False)),
            "decomposition": decompose_ttfv(
                lat["ttfv_s"], lat["queued_s"], job.warmup_s
            ),
        }
        with self._lock:
            self._obs[mode].append(row)
            self._jobs[mode] += 1
        self._h_verdict[mode].observe(row["verdict_s"])
        if row["decomposition"] is not None:
            self._h_ttfv[mode].observe(row["decomposition"]["ttfv_s"])
        self._publish(mode)

    def _mode_view(self, mode: str) -> dict:
        with self._lock:
            rows = list(self._obs[mode])
            jobs = self._jobs[mode]
        verdicts = [r["verdict_s"] for r in rows]
        decomps = [r["decomposition"] for r in rows if r["decomposition"]]
        ttfvs = [d["ttfv_s"] for d in decomps]
        compiles = [
            r["compile_s"] for r in rows if r.get("compile_s") is not None
        ]
        known_free = [
            r for r in rows if r.get("compile_free") is not None
        ]
        view = {
            "jobs": jobs,
            "window": len(rows),
            "ttfv": {
                "count": len(ttfvs),
                "p50_s": _pct(ttfvs, 50),
                "p99_s": _pct(ttfvs, 99),
            },
            "verdict": {
                "count": len(verdicts),
                "p50_s": _pct(verdicts, 50),
                "p99_s": _pct(verdicts, 99),
            },
            "compile": {
                "count": len(compiles),
                "p50_s": _pct(compiles, 50),
                "p99_s": _pct(compiles, 99),
                "free_fraction": (
                    sum(1 for r in known_free if r["compile_free"])
                    / len(known_free)
                    if known_free
                    else None
                ),
                "warm_start_jobs": sum(
                    1 for r in rows if r.get("warm_start")
                ),
            },
            "decomposition": {
                phase: {
                    "p50_s": _pct([d[phase] for d in decomps], 50),
                    "mean_s": (
                        sum(d[phase] for d in decomps) / len(decomps)
                        if decomps
                        else None
                    ),
                }
                for phase in ("queue_s", "compile_s", "explore_s")
            },
            "last": rows[-1] if rows else None,
        }
        burn = {}
        budget = 1.0 - self.objective
        if "ttfv_s" in self.targets and ttfvs:
            bad = sum(t > self.targets["ttfv_s"] for t in ttfvs)
            burn["ttfv"] = (bad / len(ttfvs)) / budget
        if "verdict_s" in self.targets and verdicts:
            bad = sum(v > self.targets["verdict_s"] for v in verdicts)
            burn["verdict"] = (bad / len(verdicts)) / budget
        if burn:
            view["burn_rate"] = burn
        return view

    def _publish(self, mode: str) -> None:
        view = self._mode_view(mode)
        self._gauge(mode, "jobs").set(view["jobs"])
        for key, block in (("ttfv", view["ttfv"]),
                           ("verdict", view["verdict"])):
            for stat in ("p50_s", "p99_s"):
                if block[stat] is not None:
                    self._gauge(mode, f"{key}_{stat}").set(block[stat])
        for phase, block in view["decomposition"].items():
            if block["p50_s"] is not None:
                self._gauge(mode, f"{phase}_p50").set(block["p50_s"])
        comp = view["compile"]
        for stat in ("p50_s", "p99_s"):
            if comp[stat] is not None:
                self._gauge(mode, f"compile_{stat}").set(comp[stat])
        if comp["free_fraction"] is not None:
            self._gauge(mode, "compile_free_fraction").set(
                comp["free_fraction"]
            )
        for key, rate in view.get("burn_rate", {}).items():
            self._gauge(mode, f"{key}_burn_rate").set(rate)

    def snapshot(self) -> dict:
        """The ``GET /slo`` body."""
        return {
            "targets": dict(self.targets),
            "objective": self.objective,
            "window": self.window,
            "modes": {m: self._mode_view(m) for m in MODES},
        }
