"""HTTP front-end for the check service.

Extends the monitor/Explorer HTTP surface with the job API::

    POST /jobs                   submit against the model zoo
                                 {"model": "2pc", "model_args": {...},
                                  "options": {...}, "spawn": {...},
                                  "priority": 0, "deadline_s": null,
                                  "tenant": "...", "hbm_budget_mib": null,
                                  "mode": "exhaustive" | "swarm",
                                  "seed": 0}
                                 (an inadmissible hbm_budget_mib is a 400
                                 at submit, not a mid-run failure;
                                 mode="swarm" runs seed-deterministic
                                 randomized walks — see README "Swarm
                                 verification")
                                 mode="conformance" instead takes
                                 {"records": [wire frames...]} or
                                 {"corpus": "<stored name>"} and replays/
                                 audits the upload — see README "Trace
                                 conformance & consistency auditing"
    GET  /jobs                   every job's status (the UI panel feed)
    GET  /jobs/<id>              one job: state, verdict, latency fields,
                                 and the honest scheduling surface —
                                 "packable" (+ "packable_reason"),
                                 "preemptible" (false = this job
                                 serializes the device), "packed" (it ran
                                 co-scheduled in shared waves)
    POST /jobs/<id>/cancel       cancel (preempts a running job)
    GET  /jobs/<id>/metrics      that job's registry, Prometheus text,
                                 labeled {run_id="<id>"}
    GET  /metrics                aggregate: default registry + every
                                 run's registry under a run_id label
    GET  /status, /events        the live-monitor endpoints (aggregate
                                 across jobs: no run filter)
    GET  /                       the Explorer UI page (the job-list
                                 panel appears when /jobs answers)

Stdlib-only, same bounded-SSE / never-block-a-checker rules as
``telemetry/server.py`` (whose routing helpers this reuses).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..checker.explorer import ui_asset
from ..telemetry.metrics import run_registries
from ..telemetry.server import (
    MonitorCore,
    _send,
    handle_monitor_get,
    prometheus_text,
    prometheus_text_all_runs,
)
from .service import CheckService, QueueFullError

# Spawn kwargs a REMOTE caller may set. Everything else is rejected:
# `resume_from` would make the server pickle.load an attacker-chosen
# path (code execution), `checkpoint_path`/`spill_dir`/`profile_dir`
# are server-side file writes at client-chosen locations, and
# `run_id`/`aot_cache` are service-managed identities. The in-process
# Python API (`CheckService.submit`) stays unrestricted — its caller
# already runs arbitrary code.
_HTTP_SPAWN_KEYS = frozenset({
    "frontier_capacity",
    "table_capacity",
    "max_drain_waves",
    "drain_log_factor",
    "pool_factor",
    "hashset_impl",
    "wave_dedup",
    "expand_fps",
    "bucket_ladder",
    "attribution",
    "coverage",
})

# Swarm fleet shape (mode="swarm" jobs; checker/swarm.py). Mode-keyed
# so a wrong-mode spawn key stays a 400 AT SUBMIT (the module
# convention), not a TypeError mid-run — an exhaustive job has no
# "lanes", a swarm job no "bucket_ladder". Note a spawn override
# honestly disqualifies a swarm job from packing.
_HTTP_SWARM_SPAWN_KEYS = frozenset({
    "lanes",
    "wave_steps",
    "max_trace_len",
    "sample_capacity",
    "sample_stride",
    "coverage",
})

# Conformance jobs (mode="conformance"; conformance/checker.py): the
# upload IS the work, so there is no model/options surface — just the
# batch shape and the host-parity gate. The upload arrives as inline
# wire frames ("records") or a named server-side corpus ("corpus");
# corpus values are NAMES resolved inside the service's CorpusStore
# root, never paths — accepting paths would hand remote clients
# arbitrary server-side file reads (the same reasoning that keeps
# `resume_from` off the HTTP spawn surface above).
_HTTP_CONFORMANCE_SPAWN_KEYS = frozenset({
    "batch_lanes",
    "parity",
})


def _json_response(handler, payload, code=200, headers=None) -> None:
    body = json.dumps(payload, default=str).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for name, value in (headers or {}).items():
        handler.send_header(name, value)
    handler.end_headers()
    handler.wfile.write(body)


class _ServiceHandler(BaseHTTPRequestHandler):
    service: CheckService = None
    core: MonitorCore = None

    def log_message(self, *args):  # quiet by default
        pass

    # -- GET ----------------------------------------------------------------

    def do_GET(self):
        try:
            if self.path == "/metrics":
                # Aggregate exposition: every job's registry under a
                # run_id label (the per-run namespacing fix means they
                # no longer merge into one colliding registry).
                _send(
                    self, prometheus_text_all_runs().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                return
            if handle_monitor_get(self, self.core, self.path):
                return
            if self.path == "/slo":
                # Per-mode SLO view (service/slo.py): rolling ttfv /
                # verdict percentiles, queue/compile/explore ttfv
                # decomposition, burn rates when targets are set.
                _json_response(self, self.service.slo.snapshot())
                return
            if self.path == "/jobs":
                # Summary view: the UI polls this every ~2s; full
                # verdicts (report text, ledgers) stay on /jobs/<id>.
                _json_response(self, {
                    "jobs": [j.summary() for j in self.service.jobs()],
                })
                return
            if self.path.startswith("/jobs/"):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) < 2:  # bare "/jobs/" (trailing slash)
                    _json_response(self, {"error": "no such job"}, 404)
                    return
                job = self.service.job(parts[1])
                if job is None:
                    _json_response(self, {"error": "no such job"}, 404)
                    return
                if len(parts) == 2:
                    _json_response(self, job.status())
                elif len(parts) == 3 and parts[2] == "metrics":
                    # Look up, never create: a GET for a job that has
                    # not run yet must not leak an empty registry into
                    # the process-wide run index.
                    reg = run_registries().get(job.run_id)
                    body = (
                        prometheus_text(
                            reg, labels={"run_id": job.run_id}
                        )
                        if reg is not None
                        else "\n"
                    )
                    _send(
                        self,
                        body.encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                else:
                    _json_response(self, {"error": "not found"}, 404)
                return
            self._static(self.path)
        except ConnectionError:
            pass  # routine client disconnect mid-response

    # -- POST ---------------------------------------------------------------

    def do_POST(self):
        try:
            if self.path == "/jobs":
                self._submit()
                return
            parts = [p for p in self.path.split("/") if p]
            if (
                len(parts) == 3
                and parts[0] == "jobs"
                and parts[2] == "cancel"
            ):
                job = self.service.job(parts[1])
                if job is None:
                    _json_response(self, {"error": "no such job"}, 404)
                    return
                from .jobs import JobHandle

                cancelled = JobHandle(job, self.service).cancel()
                _json_response(self, {
                    "job_id": job.job_id, "cancelled": cancelled,
                })
                return
            _json_response(self, {"error": "not found"}, 404)
        except ConnectionError:
            pass

    def _submit(self) -> None:
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, json.JSONDecodeError):
            _json_response(self, {"error": "invalid JSON body"}, 400)
            return
        mode = body.get("mode") or "exhaustive"
        if mode == "conformance":
            self._submit_conformance(body)
            return
        name = body.get("model")
        if not name:
            _json_response(
                self,
                {"error": "missing 'model'",
                 "zoo": sorted(self.service.zoo)},
                400,
            )
            return
        spawn = body.get("spawn") or {}
        if not isinstance(spawn, dict):
            _json_response(self, {"error": "spawn must be an object"}, 400)
            return
        allowed = (
            _HTTP_SWARM_SPAWN_KEYS if mode == "swarm" else _HTTP_SPAWN_KEYS
        )
        blocked = set(spawn) - allowed
        if blocked:
            _json_response(
                self,
                {"error": f"spawn keys not allowed over HTTP for "
                          f"mode={mode!r}: {sorted(blocked)}",
                 "allowed": sorted(allowed)},
                400,
            )
            return
        submit_kwargs = {}
        if "retry" in body:
            retry = body.get("retry")
            if retry is not None and not isinstance(retry, dict):
                _json_response(
                    self, {"error": "retry must be an object"}, 400
                )
                return
            submit_kwargs["retry_policy"] = retry
        try:
            # Raw values through: submit() coerces priority/deadline/
            # budget itself and raises ValueError on garbage (a list
            # priority must 400 here, not TypeError the handler).
            handle = self.service.submit(
                model_name=name,
                model_args=body.get("model_args") or {},
                options=body.get("options") or {},
                spawn=spawn,
                priority=body.get("priority") or 0,
                deadline_s=body.get("deadline_s"),
                tenant=body.get("tenant"),
                hbm_budget_mib=body.get("hbm_budget_mib"),
                timeout_s=body.get("timeout_s"),
                mode=body.get("mode") or "exhaustive",
                seed=body.get("seed") or 0,
                **submit_kwargs,
            )
        except QueueFullError as e:
            # Graceful degradation: a full admission queue is 429 with
            # a Retry-After hint, not a 400 the client would never
            # retry.
            _json_response(
                self,
                {"error": str(e), "retry_after_s": e.retry_after_s},
                429,
                headers={"Retry-After": str(max(1, int(e.retry_after_s)))},
            )
            return
        except (ValueError, RuntimeError) as e:
            _json_response(self, {"error": str(e)}, 400)
            return
        _json_response(
            self, {"job_id": handle.job_id, **handle.status()}, 201
        )

    def _submit_conformance(self, body) -> None:
        """mode="conformance" submissions: {"records": [frames...]} for
        inline wire frames, or {"corpus": "<name>"} naming a server-side
        corpus. Malformed frames are 400s carrying the wire refusal
        (line number + reason), not mid-run failures."""
        records = body.get("records")
        corpus = body.get("corpus")
        if (records is None) == (corpus is None):
            _json_response(
                self,
                {"error": "conformance jobs take exactly one of "
                          "'records' (inline wire frames) or 'corpus' "
                          "(a stored corpus name)"},
                400,
            )
            return
        spawn = body.get("spawn") or {}
        if not isinstance(spawn, dict):
            _json_response(self, {"error": "spawn must be an object"}, 400)
            return
        blocked = set(spawn) - _HTTP_CONFORMANCE_SPAWN_KEYS
        if blocked:
            _json_response(
                self,
                {"error": f"spawn keys not allowed over HTTP for "
                          f"mode='conformance': {sorted(blocked)}",
                 "allowed": sorted(_HTTP_CONFORMANCE_SPAWN_KEYS)},
                400,
            )
            return
        if corpus is not None:
            store = getattr(self.service, "corpus_store", None)
            if store is None:
                _json_response(
                    self,
                    {"error": "no corpus store: the service has no "
                              "service_dir (submit inline 'records' "
                              "instead)"},
                    400,
                )
                return
            try:
                # A NAME resolved inside the store root — never a path
                # (validate_corpus_name rejects separators).
                records = store.load(corpus)
            except ValueError as e:
                _json_response(self, {"error": str(e)}, 400)
                return
            except FileNotFoundError:
                _json_response(
                    self,
                    {"error": f"no such corpus {corpus!r}",
                     "corpora": store.list()},
                    400,
                )
                return
        submit_kwargs = {}
        if "retry" in body:
            retry = body.get("retry")
            if retry is not None and not isinstance(retry, dict):
                _json_response(
                    self, {"error": "retry must be an object"}, 400
                )
                return
            submit_kwargs["retry_policy"] = retry
        try:
            handle = self.service.submit(
                conformance=records,
                mode="conformance",
                spawn=spawn,
                priority=body.get("priority") or 0,
                deadline_s=body.get("deadline_s"),
                tenant=body.get("tenant"),
                timeout_s=body.get("timeout_s"),
                **submit_kwargs,
            )
        except QueueFullError as e:
            _json_response(
                self,
                {"error": str(e), "retry_after_s": e.retry_after_s},
                429,
                headers={"Retry-After": str(max(1, int(e.retry_after_s)))},
            )
            return
        except (ValueError, RuntimeError) as e:
            # WireRefusal is a ValueError: a malformed frame 400s with
            # its line number and reason, at submit.
            _json_response(self, {"error": str(e)}, 400)
            return
        _json_response(
            self, {"job_id": handle.job_id, **handle.status()}, 201
        )

    # -- static UI (the Explorer page; its job panel polls /jobs) -----------

    def _static(self, path: str) -> None:
        asset = ui_asset(path)
        if asset is None:
            _json_response(self, {"error": "not found"}, 404)
            return
        content_type, body = asset
        _send(self, body, content_type)


class ServiceServer:
    """``CheckService`` + HTTP on a daemon thread.

    ::

        server = ServiceServer(port=8791)       # owns a fresh service
        ... curl -X POST :8791/jobs -d '{"model": "2pc"}' ...
        server.close()

    Pass an existing ``service=`` to front it without owning its
    lifecycle (``close()`` then leaves the service running)."""

    def __init__(self, service: Optional[CheckService] = None, port: int = 0,
                 host: str = "127.0.0.1", run_id: Optional[str] = None,
                 **service_kwargs):
        self._owns_service = service is None
        self.service = (
            service if service is not None else CheckService(**service_kwargs)
        )
        # Aggregate monitor core (no run filter): every job's wave spans
        # feed one estimator — the whole-device states/s view.
        self.core = MonitorCore(run_id=run_id)
        try:
            handler = type(
                "Handler",
                (_ServiceHandler,),
                {"service": self.service, "core": self.core},
            )
            self._server = ThreadingHTTPServer((host, port), handler)
        except BaseException:
            self.core.close()
            if self._owns_service:
                self.service.close()
            raise
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="service-http",
            daemon=True,
        )
        self._thread.start()
        self.core.tracer.instant(
            "service.started", port=self.port, run_id=self.core.run_id
        )

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self.core.close()
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        if self._owns_service:
            self.service.close()

    def __enter__(self) -> "ServiceServer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
