"""Register-protocol adapter: a message interface for register-like actors and
a client actor for model checking them against a ``ConsistencyTester``.

Clients do ``put_count`` Puts followed by a Get, round-robining servers via
``(index + op_count) % server_count``; ``record_invocations``/
``record_returns`` plug the message flow into any consistency tester used as
``ActorModel`` history.

Reference: ``/root/reference/src/actor/register.rs``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..semantics.register import READ, ReadOk, Write, WRITE_OK
from .actor import Actor, Id, Out
from .network import Envelope


# -- the register message interface ------------------------------------------


@dataclass(frozen=True)
class Internal:
    """A message specific to the register system's internal protocol."""

    msg: object

    def __repr__(self):
        return f"Internal({self.msg!r})"


@dataclass(frozen=True)
class Put:
    request_id: int
    value: object

    def __repr__(self):
        return f"Put({self.request_id!r}, {self.value!r})"


@dataclass(frozen=True)
class Get:
    request_id: int

    def __repr__(self):
        return f"Get({self.request_id!r})"


@dataclass(frozen=True)
class PutOk:
    request_id: int

    def __repr__(self):
        return f"PutOk({self.request_id!r})"


@dataclass(frozen=True)
class GetOk:
    request_id: int
    value: object

    def __repr__(self):
        return f"GetOk({self.request_id!r}, {self.value!r})"


# -- history hooks -----------------------------------------------------------


def record_invocations(_cfg, history, env: Envelope):
    """Pass to ``ActorModel.record_msg_out``: records Read on Get, Write on
    Put (into a cloned tester; invalid histories are swallowed, matching the
    reference)."""
    if isinstance(env.msg, Get):
        h = history.clone()
        try:
            h.on_invoke(env.src, READ)
        except ValueError:
            pass
        return h
    if isinstance(env.msg, Put):
        h = history.clone()
        try:
            h.on_invoke(env.src, Write(env.msg.value))
        except ValueError:
            pass
        return h
    return None


def record_returns(_cfg, history, env: Envelope):
    """Pass to ``ActorModel.record_msg_in``: records ReadOk on GetOk, WriteOk
    on PutOk."""
    if isinstance(env.msg, GetOk):
        h = history.clone()
        try:
            h.on_return(env.dst, ReadOk(env.msg.value))
        except ValueError:
            pass
        return h
    if isinstance(env.msg, PutOk):
        h = history.clone()
        try:
            h.on_return(env.dst, WRITE_OK)
        except ValueError:
            pass
        return h
    return None


# -- the model-checking client actor -----------------------------------------


@dataclass(frozen=True)
class ClientState:
    awaiting: Optional[int]
    op_count: int

    def __repr__(self):
        return f"Client {{ awaiting: {self.awaiting!r}, op_count: {self.op_count!r} }}"


class RegisterClient(Actor):
    """A client that Puts ``put_count`` values then Gets, round-robining
    servers. Servers must precede clients in the actor list so destinations
    derive from ``(client_index + k) % server_count``."""

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def name(self) -> str:
        return "Client"

    def on_start(self, id: Id, o: Out) -> ClientState:
        index = int(id)
        server_count = self.server_count
        if index < server_count:
            raise ValueError(
                "RegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return ClientState(awaiting=None, op_count=0)
        unique_request_id = index  # next will be 2 * index
        value = chr(ord("A") + (index - server_count))
        o.send(Id(index % server_count), Put(unique_request_id, value))
        return ClientState(awaiting=unique_request_id, op_count=1)

    def _completes_put(self, msg) -> bool:
        """Whether ``msg`` completes an outstanding Put (the write-once
        variant also accepts PutFail)."""
        return isinstance(msg, PutOk)

    def on_msg(self, id: Id, state: ClientState, src: Id, msg, o: Out):
        if not isinstance(state, ClientState) or state.awaiting is None:
            return None
        index = int(id)
        server_count = self.server_count
        if self._completes_put(msg) and msg.request_id == state.awaiting:
            unique_request_id = (state.op_count + 1) * index
            if state.op_count < self.put_count:
                value = chr(ord("Z") - (index - server_count))
                o.send(
                    Id((index + state.op_count) % server_count),
                    Put(unique_request_id, value),
                )
            else:
                o.send(
                    Id((index + state.op_count) % server_count),
                    Get(unique_request_id),
                )
            return ClientState(
                awaiting=unique_request_id, op_count=state.op_count + 1
            )
        if isinstance(msg, GetOk) and msg.request_id == state.awaiting:
            return ClientState(awaiting=None, op_count=state.op_count + 1)
        return None
