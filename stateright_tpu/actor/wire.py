"""JSON wire codecs for the register message protocol (used by ``spawn``).

Format matches the reference examples' serde-JSON representation, e.g.
``{"Put": [1, "X"]}``, ``{"Get": [2]}``, ``{"PutOk": [1]}``,
``{"GetOk": [2, "X"]}``, ``{"Internal": ...}``.
"""

from __future__ import annotations

import json

from .register import Get, GetOk, Internal, Put, PutOk


def register_msg_to_wire(msg) -> bytes:
    if isinstance(msg, Put):
        doc = {"Put": [msg.request_id, _value_to_doc(msg.value)]}
    elif isinstance(msg, Get):
        doc = {"Get": [msg.request_id]}
    elif isinstance(msg, PutOk):
        doc = {"PutOk": [msg.request_id]}
    elif isinstance(msg, GetOk):
        doc = {"GetOk": [msg.request_id, _value_to_doc(msg.value)]}
    elif isinstance(msg, Internal):
        doc = {"Internal": _value_to_doc(msg.msg)}
    else:
        doc = _value_to_doc(msg)
    return json.dumps(doc).encode()


def register_msg_from_wire(data: bytes):
    doc = json.loads(data.decode())
    if isinstance(doc, dict):
        if "Put" in doc:
            return Put(doc["Put"][0], _doc_to_value(doc["Put"][1]))
        if "Get" in doc:
            return Get(doc["Get"][0])
        if "PutOk" in doc:
            return PutOk(doc["PutOk"][0])
        if "GetOk" in doc:
            return GetOk(doc["GetOk"][0], _doc_to_value(doc["GetOk"][1]))
        if "Internal" in doc:
            return Internal(_doc_to_value(doc["Internal"]))
    return _doc_to_value(doc)


def _value_to_doc(value):
    """Tuples become lists (JSON has no tuple type)."""
    if isinstance(value, tuple):
        return [_value_to_doc(v) for v in value]
    if isinstance(value, list):
        return [_value_to_doc(v) for v in value]
    if isinstance(value, dict):
        return {k: _value_to_doc(v) for k, v in value.items()}
    return value


def _doc_to_value(doc):
    """Lists become tuples so deserialized messages hash/compare like the
    originals."""
    if isinstance(doc, list):
        return tuple(_doc_to_value(v) for v in doc)
    if isinstance(doc, dict):
        return {k: _doc_to_value(v) for k, v in doc.items()}
    return doc
