"""The event-driven actor abstraction.

Reference: ``Actor`` trait at ``/root/reference/src/actor.rs:270-341``, ``Id``
at ``:108-156``, ``Out``/``Command`` at ``:159-243``.

Python adaptation of the reference's copy-on-write no-op detection: callbacks
*return* the next actor state (or ``None`` for "unchanged"). Returning a state
object — even one equal to the previous state — counts as a write, exactly
like the reference's ``Cow::Owned``; this distinction is load-bearing for
state-count parity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Generic, List, Optional, Tuple, TypeVar

Msg = TypeVar("Msg")
Timer = TypeVar("Timer")
State = TypeVar("State")


class Id(int):
    """Uniquely identifies an actor. An index for model-checked actors; encodes
    a socket address for spawned actors (see ``stateright_tpu.actor.spawn``)."""

    def __repr__(self) -> str:
        return f"Id({int(self)})"

    @staticmethod
    def vec_from(ids) -> List["Id"]:
        return [Id(i) for i in ids]

    @staticmethod
    def from_socket_addr(ip: str, port: int) -> "Id":
        """Encodes an IPv4 address + port: IP in bytes 2-5, port in bytes 6-7
        (reference: ``/root/reference/src/actor/spawn.rs:10-34``)."""
        octets = [int(o) for o in ip.split(".")]
        value = 0
        for o in octets:
            value = (value << 8) | o
        return Id((value << 16) | port)

    def socket_addr(self) -> Tuple[str, int]:
        port = int(self) & 0xFFFF
        ip_bits = (int(self) >> 16) & 0xFFFFFFFF
        ip = ".".join(str((ip_bits >> shift) & 0xFF) for shift in (24, 16, 8, 0))
        return ip, port


# Command kinds (reference: Command enum at /root/reference/src/actor.rs:159-167)
SEND = "Send"
SET_TIMER = "SetTimer"
CANCEL_TIMER = "CancelTimer"


@dataclass(frozen=True)
class Command:
    kind: str
    # Send: (dst, msg); SetTimer: (timer, duration_range); CancelTimer: (timer,)
    args: tuple

    @staticmethod
    def send(dst: Id, msg) -> "Command":
        return Command(SEND, (dst, msg))

    @staticmethod
    def set_timer(timer, duration_range) -> "Command":
        return Command(SET_TIMER, (timer, duration_range))

    @staticmethod
    def cancel_timer(timer) -> "Command":
        return Command(CANCEL_TIMER, (timer,))


class Out(Generic[Msg, Timer]):
    """Holds commands output by an actor callback."""

    def __init__(self):
        self.commands: List[Command] = []

    def send(self, recipient: Id, msg) -> None:
        self.commands.append(Command.send(recipient, msg))

    def broadcast(self, recipients, msg) -> None:
        for recipient in recipients:
            self.send(recipient, msg)

    def set_timer(self, timer, duration_range=None) -> None:
        """Set/reset a named timer. ``duration_range`` is a (lo, hi) seconds
        tuple for the spawned runtime; irrelevant under model checking (use
        ``model_timeout()``)."""
        self.commands.append(Command.set_timer(timer, duration_range))

    def cancel_timer(self, timer) -> None:
        self.commands.append(Command.cancel_timer(timer))

    def append(self, other: "Out") -> None:
        self.commands.extend(other.commands)
        other.commands.clear()

    def __len__(self) -> int:
        return len(self.commands)

    def __iter__(self):
        return iter(self.commands)

    def __repr__(self) -> str:
        return repr(self.commands)


def is_no_op(returned_state, out: Out) -> bool:
    """True iff the actor neither returned a new state nor output commands."""
    return returned_state is None and not out.commands


def is_no_op_with_timer(returned_state, out: Out, timer) -> bool:
    """True iff the actor only renewed the same timer (and didn't change
    state or output anything else)."""
    keep_timer = any(
        c.kind == SET_TIMER and c.args[0] == timer for c in out.commands
    )
    unmodified_out = len(out.commands) == 1 and keep_timer
    return returned_state is None and unmodified_out


class Actor(Generic[Msg, Timer, State]):
    """An actor initializes internal state (optionally emitting commands), then
    waits for incoming events, responding by returning an updated state and/or
    emitting commands.

    Callbacks return the next actor state or ``None`` for "no change"."""

    def on_start(self, id: Id, o: Out) -> State:
        """Returns the initial state; may emit commands via ``o``."""
        raise NotImplementedError

    def on_msg(self, id: Id, state: State, src: Id, msg, o: Out) -> Optional[State]:
        """Handles a message. Returns the next state, or None if unchanged."""
        return None

    def on_timeout(self, id: Id, state: State, timer, o: Out) -> Optional[State]:
        """Handles a timeout. Returns the next state, or None if unchanged."""
        return None

    def name(self) -> str:
        return ""
