"""Packed helpers for register-protocol actor systems (paxos / ABD / ...).

The reference's register harness (``/root/reference/src/actor/register.rs``)
pairs protocol servers with ``RegisterActor`` clients and plugs the message
flow into a consistency tester via history hooks. This module is the packed
twin shared by every such codec:

- canonical message kind codes for the client-facing protocol (codecs place
  their internal protocol kinds at ``KIND_INTERNAL_BASE`` and up);
- pack/unpack + the traceable ``on_msg`` kernel for ``RegisterClient`` rows;
- the history routing hooks mapping Put/Get sends to tester invocations and
  PutOk/GetOk deliveries to returns (host analogs: ``record_invocations`` /
  ``record_returns`` in ``stateright_tpu/actor/register.py``).
"""

from __future__ import annotations

import numpy as np

from .packed import ActorPackedCodec
from .register import ClientState

# Client-facing message kinds, shared across register-protocol codecs.
# 0 is reserved (empty envelope slots hash as zeros).
K_PUT, K_GET, K_PUT_OK, K_GET_OK = 1, 2, 3, 4
KIND_INTERNAL_BASE = 5

# Client rows are [has_awaiting, awaiting, op_count]; codecs pad to their
# server row width.
CLIENT_ROW_WORDS = 3


def pack_client_state(state: ClientState, width: int) -> np.ndarray:
    row = np.zeros((width,), np.uint32)
    if state.awaiting is not None:
        row[0] = 1
        row[1] = state.awaiting
    row[2] = state.op_count
    return row


def unpack_client_state(row) -> ClientState:
    return ClientState(
        awaiting=int(row[1]) if int(row[0]) else None,
        op_count=int(row[2]),
    )


def client_on_msg_branch(codec, put_count: int, server_count: int):
    """The traceable twin of ``RegisterClient.on_msg``: PutOk advances to the
    next Put or the final Get; GetOk completes the run. Round-robin
    destination ``(index + op_count) % server_count``, request id
    ``(op_count + 1) * index``, values ``'Z' - (index - server_count)``."""
    import jax.numpy as jnp

    u = jnp.uint32
    W = codec.msg_width

    def no_sends():
        return jnp.full((codec.send_capacity, 1 + W), codec.SEND_NONE)

    def msg_vec(kind, req, val):
        vec = jnp.zeros((W,), u)
        vec = vec.at[0].set(kind).at[1].set(req)
        return vec.at[2].set(val)

    def on_msg(me, row, src, msg):
        kind, req = msg[0], msg[1]
        has_aw, aw, opc = row[0], row[1], row[2]
        meu = me.astype(u)
        sc = u(server_count)

        awaited = (has_aw == 1) & (req == aw)
        put_done = (kind == u(K_PUT_OK)) & awaited
        get_done = (kind == u(K_GET_OK)) & awaited

        nreq = (opc + 1) * meu
        dst = (meu + opc) % sc
        more_puts = opc < u(put_count)
        zval = u(ord("Z")) - (meu - sc)
        next_msg = jnp.where(
            more_puts, msg_vec(u(K_PUT), nreq, zval), msg_vec(u(K_GET), nreq, u(0))
        )
        p_sends = no_sends().at[0].set(
            jnp.concatenate([dst[None], next_msg])
        )
        p_row = row.at[0].set(u(1)).at[1].set(nreq).at[2].set(opc + 1)
        g_row = row.at[0].set(u(0)).at[1].set(u(0)).at[2].set(opc + 1)

        row_out = jnp.where(put_done, p_row, jnp.where(get_done, g_row, row))
        sends = jnp.where(put_done, p_sends, no_sends())
        changed = put_done | get_done
        zero = u(0)
        return row_out, sends, zero, zero, changed

    return on_msg


def make_history_hooks(lin, server_count: int):
    """(history_on_deliver, history_on_send) for a codec whose client threads
    are actors ``server_count..N`` and whose messages use the kind codes
    above. ``lin`` is a ``PackedRegisterLinearizability``."""
    import jax.numpy as jnp

    u = jnp.uint32
    C = lin.C

    def on_send(model, hist, src, dst, msg):
        # record_invocations: a Put/Get entering the network invokes
        # Write/Read for thread = the sender.
        kind = msg[0]
        is_put = kind == u(K_PUT)
        is_get = kind == u(K_GET)
        c = jnp.clip(src - server_count, 0, C - 1).astype(jnp.int32)
        active = (src >= server_count) & (is_put | is_get)
        op_kind = jnp.where(is_put, u(1), u(2))
        return lin.on_invoke(hist, c, op_kind, msg[2], active)

    def on_deliver(model, hist, src, dst, msg):
        # record_returns: a PutOk/GetOk delivered to a client returns
        # WriteOk/ReadOk(value) for thread = the recipient.
        kind = msg[0]
        is_ret = (kind == u(K_PUT_OK)) | (kind == u(K_GET_OK))
        c = jnp.clip(dst - server_count, 0, C - 1).astype(jnp.int32)
        active = (dst >= server_count) & is_ret
        return lin.on_return(hist, c, msg[2], active)

    return on_deliver, on_send


def trace_helpers(codec, server_count: int):
    """(no_sends, send_row, broadcast) builders shared by server kernels:
    a blank send table, one send row ``[dst, words..., pad]``, and a
    broadcast giving every server its own row with ``me``'s left blank."""
    import jax.numpy as jnp

    u = jnp.uint32
    W = codec.msg_width
    S = codec.send_capacity

    def no_sends():
        return jnp.full((S, 1 + W), codec.SEND_NONE)

    def send_row(dst, *words):
        vec = jnp.zeros((1 + W,), u).at[0].set(dst)
        for k, w in enumerate(words):
            vec = vec.at[1 + k].set(w)
        return vec

    def broadcast(me, *words):
        rows = no_sends()
        meu = me.astype(u)
        for s in range(server_count):
            row = send_row(u(s), *words)
            rows = rows.at[s].set(jnp.where(u(s) == meu, rows[s], row))
        return rows

    return no_sends, send_row, broadcast


class RegisterProtocolCodec(ActorPackedCodec):
    """Shared base for register-protocol codecs (paxos / ABD / single-copy):
    servers are actor type 0, clients type 1, and the auxiliary history is a
    packed ``LinearizabilityTester`` with the standard hooks + conditions
    (``always linearizable``, ``sometimes value chosen``)."""

    put_count = 1

    def _init_register_protocol(self, client_count, server_count, default_value):
        from ..semantics.packed_linearizability import (
            PackedRegisterLinearizability,
        )

        self.client_count = client_count
        self.server_count = server_count
        self._lin = PackedRegisterLinearizability(
            thread_ids=range(server_count, server_count + client_count),
            ops_per_thread=self.put_count + 1,
            default_value=default_value,
        )
        self.history_width = self._lin.width

    def actor_type_id(self, i, actor) -> int:
        return 0 if i < self.server_count else 1

    def pack_history(self, history) -> np.ndarray:
        return self._lin.pack(history)

    def unpack_history(self, vec):
        return self._lin.unpack(vec)

    def history_on_deliver(self, model, hist, src, dst, msg):
        return self._hooks()[0](model, hist, src, dst, msg)

    def history_on_send(self, model, hist, src, dst, msg):
        return self._hooks()[1](model, hist, src, dst, msg)

    def _hooks(self):
        if not hasattr(self, "_hooks_cache"):
            self._hooks_cache = make_history_hooks(
                self._lin, self.server_count
            )
        return self._hooks_cache

    def packed_conditions(self, model):
        lin_ok = self._lin.predicate()
        return [
            lambda state: lin_ok(state["hist"]),
            value_chosen_condition(model),
        ]


def value_chosen_condition(model):
    """Traceable twin of the examples' ``sometimes "value chosen"``: some
    deliverable GetOk carries a non-default value. For ordered networks
    "deliverable" means flow heads only (host ``iter_deliverable``)."""
    import jax.numpy as jnp

    if model._ordered:

        def cond(state):
            head = state["flow_msg"][:, 0, :]
            live = state["flow_len"] > 0
            return (
                live & (head[:, 0] == jnp.uint32(K_GET_OK)) & (head[:, 2] != 0)
            ).any()

    else:

        def cond(state):
            kind = state["net_msg"][:, 0]
            val = state["net_msg"][:, 2]
            live = state["net_cnt"] > 0
            return (
                live & (kind == jnp.uint32(K_GET_OK)) & (val != 0)
            ).any()

    return cond


def register_flow_pairs(client_count: int, server_count: int):
    """Directed flow pairs a register-protocol system can ever use on an
    ordered network: every ``(src, dst)`` pair except self-pairs and
    client-to-client — clients only message servers; servers message
    clients and (protocol-internal, e.g. ABD replication) other servers.
    For 3 clients / 2 servers this keeps 14 of 25 pairs, shrinking the
    packed flow table and the deliver/drop action grid accordingly
    (``PackedActorModel.with_flow_pairs``). Exactness is pinned by the
    bench-family count oracles: an excluded pair that the protocol in
    fact uses would prune transitions and fail them loudly."""
    n = server_count + client_count
    return [
        (a, b)
        for a in range(n)
        for b in range(n)
        if a != b and not (a >= server_count and b >= server_count)
    ]
