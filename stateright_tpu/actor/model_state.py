"""System snapshot for an actor model.

NOTE (reference parity): hashing and equality cover actor_states / history /
timers_set / network but deliberately NOT ``crashed`` — matching the
reference's manual ``Hash``/``PartialEq`` impls
(``/root/reference/src/actor/model_state.rs:86-112``). A Crash transition with
no set timers therefore fingerprints identically to its parent state.
"""

from __future__ import annotations

from typing import List

from .network import Network
from .timers import Timers


class ActorModelState:
    """Snapshot in time for the entire actor system."""

    __slots__ = ("actor_states", "network", "timers_set", "crashed", "history")

    def __init__(
        self,
        actor_states: List,
        network: Network,
        timers_set: List[Timers],
        crashed: List[bool],
        history,
    ):
        self.actor_states = actor_states
        self.network = network
        self.timers_set = timers_set
        self.crashed = crashed
        self.history = history

    def copy(self) -> "ActorModelState":
        return ActorModelState(
            actor_states=list(self.actor_states),
            network=self.network.copy(),
            timers_set=[t.copy() for t in self.timers_set],
            crashed=list(self.crashed),
            history=self.history,
        )

    def __stable_fields__(self):
        # `crashed` intentionally excluded (see module docstring).
        return (
            tuple(self.actor_states),
            self.history,
            tuple(self.timers_set),
            self.network,
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ActorModelState)
            and self.actor_states == other.actor_states
            and self.history == other.history
            and self.timers_set == other.timers_set
            and self.network == other.network
        )

    def __hash__(self) -> int:
        from ..core.fingerprint import stable_hash

        return stable_hash(self.__stable_fields__())

    def _permuted(self, plan) -> "ActorModelState":
        """The symmetry group action: permute actor-indexed vectors and
        rewrite every embedded Id per ``plan``."""
        from ..utils.rewrite import rewrite_value

        return ActorModelState(
            actor_states=plan.reindex(self.actor_states),
            network=rewrite_network(self.network, plan),
            timers_set=plan.reindex(self.timers_set),
            crashed=plan.reindex(self.crashed),
            history=rewrite_value(self.history, plan),
        )

    def representative(self) -> "ActorModelState":
        """Sort-heuristic member of this state's symmetry equivalence class
        (reference parity: ``/root/reference/src/actor/model_state.rs:115-132``).

        NOT a canonical form — id rewriting changes the sorted rows, so
        symmetry-reduced counts under this heuristic depend on traversal
        order. ``orbit_representative`` is the proper alternative."""
        from ..utils.rewrite import RewritePlan

        return self._permuted(RewritePlan.from_values_to_sort(self.actor_states))

    def orbit_representative(self) -> "ActorModelState":
        """True orbit canonical form (see ``utils.rewrite.orbit_min``): the
        same semantics as the device checkers' minimum-fingerprint symmetry
        key, so host and device symmetry-reduced counts agree exactly."""
        from ..utils.rewrite import orbit_min

        return orbit_min(len(self.actor_states), self._permuted)

    def __repr__(self) -> str:
        return (
            "ActorModelState { "
            f"actor_states: {self.actor_states!r}, "
            f"history: {self.history!r}, "
            f"is_timer_set: {self.timers_set!r}, "
            f"network: {self.network!r} }}"
        )


def rewrite_network(network: Network, plan) -> Network:
    """Rewrites all actor Ids in a network per a RewritePlan."""
    from ..utils.rewrite import rewrite_value
    from .network import Envelope

    rewritten = Network(network.kind)
    for env in network.iter_all():
        rewritten.send(
            Envelope(
                src=plan.rewrite_id(env.src),
                dst=plan.rewrite_id(env.dst),
                msg=rewrite_value(env.msg, plan),
            )
        )
    return rewritten
