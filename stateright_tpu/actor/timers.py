"""Per-actor set of named pending timers.

Durations are irrelevant under model checking (``model_timeout()`` is a
zero-length range); a timeout action is enumerated for every set timer.

Reference: ``Timers`` at ``/root/reference/src/actor/timers.rs``. The packed
TPU representation is a bitmask per actor.
"""

from __future__ import annotations

from typing import Dict, Iterator


class Timers:
    """A collection of timers that have been set for a given actor."""

    def __init__(self, timers=()):
        # dict-as-set: deterministic insertion-order iteration.
        self._set: Dict = {t: True for t in timers}

    def set(self, timer) -> bool:
        if timer in self._set:
            return False
        self._set[timer] = True
        return True

    def cancel(self, timer) -> bool:
        return self._set.pop(timer, None) is not None

    def cancel_all(self) -> None:
        self._set.clear()

    def __iter__(self) -> Iterator:
        return iter(self._set)

    def __contains__(self, timer) -> bool:
        return timer in self._set

    def __len__(self) -> int:
        return len(self._set)

    def copy(self) -> "Timers":
        return Timers(self._set)

    def __stable_fields__(self):
        # Order-insensitive, like the reference's HashableHashSet.
        return (frozenset_safe(self._set),)

    def __eq__(self, other) -> bool:
        return isinstance(other, Timers) and set(self._set) == set(other._set)

    def __hash__(self) -> int:
        from ..core.fingerprint import stable_hash

        return stable_hash(self.__stable_fields__())

    def __repr__(self) -> str:
        return f"Timers({list(self._set)!r})"


def frozenset_safe(items):
    """A frozenset when elements are Python-hashable, else a stable-sorted
    tuple keyed by stable hash."""
    try:
        return frozenset(items)
    except TypeError:
        from ..core.fingerprint import stable_hash

        return tuple(sorted(items, key=stable_hash))
