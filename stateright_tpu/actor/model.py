"""ActorModel: the bridge from actors to the ``Model`` interface.

A system of actors communicating over a modeled ``Network`` becomes a
nondeterministic transition system whose actions are message deliveries/drops,
timeouts, and crash faults. ``H`` is an auxiliary history variable (TLA-style)
threaded through message hooks — e.g. a linearizability tester.

Reference: ``ActorModel`` at ``/root/reference/src/actor/model.rs:23-649``.
This is the prime candidate for the fixed-width staged transition function on
TPU (bounded actors, bounded message slots, dense action table — see
``stateright_tpu.models.packing``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

from ..core.model import Expectation, Model, Property
from .actor import (
    CANCEL_TIMER,
    SEND,
    SET_TIMER,
    Actor,
    Id,
    Out,
    is_no_op,
    is_no_op_with_timer,
)
from .model_state import ActorModelState
from .network import Envelope, Network, ORDERED
from .timers import Timers

LOSSY = True
LOSSLESS = False


def model_timeout():
    """An arbitrary timeout range for model checking (the specific value is
    irrelevant: timeouts fire nondeterministically)."""
    return (0, 0)


def model_peers(self_ix: int, count: int) -> List[Id]:
    """The peer Ids for actor ``self_ix`` among ``count`` actors."""
    return [Id(j) for j in range(count) if j != self_ix]


# -- actions -----------------------------------------------------------------


@dataclass(frozen=True)
class DeliverAction:
    src: Id
    dst: Id
    msg: object

    def __repr__(self):
        return f"Deliver {{ src: {self.src!r}, dst: {self.dst!r}, msg: {self.msg!r} }}"


@dataclass(frozen=True)
class DropAction:
    envelope: Envelope

    def __repr__(self):
        return f"Drop({self.envelope!r})"


@dataclass(frozen=True)
class TimeoutAction:
    id: Id
    timer: object

    def __repr__(self):
        return f"Timeout({self.id!r}, {self.timer!r})"


@dataclass(frozen=True)
class CrashAction:
    id: Id

    def __repr__(self):
        return f"Crash({self.id!r})"


class ActorModel(Model):
    """Represents a system of actors that communicate over a network.

    Builder usage::

        model = (ActorModel(cfg, init_history)
                 .actor(Server())
                 .actors(Client() for _ in range(2))
                 .init_network(Network.new_ordered())
                 .lossy_network(True)
                 .max_crashes(1)
                 .property(Expectation.ALWAYS, "safe", lambda m, s: ...)
                 .record_msg_in(lambda cfg, history, env: ... or None)
                 .within_boundary(lambda cfg, state: ...))
    """

    def __init__(self, cfg=None, init_history=None):
        self.actors_list: List[Actor] = []
        self.cfg = cfg
        self.init_history = init_history
        self._init_network: Network = Network.new_unordered_duplicating()
        self._lossy_network: bool = LOSSLESS
        self._max_crashes: int = 0
        self._properties: List[Property] = []
        # Original append positions, kept parallel to ``_properties`` so a
        # codec's positionally-aligned ``packed_conditions`` list can be
        # filtered consistently after ``retain_properties``.
        self._property_codec_pos: List[int] = []
        self._properties_added: int = 0
        self._record_msg_in: Callable = lambda cfg, history, env: None
        self._record_msg_out: Callable = lambda cfg, history, env: None
        self._within_boundary: Callable = lambda cfg, state: True

    # -- builder -------------------------------------------------------------

    def actor(self, actor: Actor) -> "ActorModel":
        self.actors_list.append(actor)
        return self

    def actors(self, actors) -> "ActorModel":
        for actor in actors:
            self.actors_list.append(actor)
        return self

    def init_network(self, network: Network) -> "ActorModel":
        self._init_network = network
        return self

    def lossy_network(self, lossy: bool) -> "ActorModel":
        self._lossy_network = lossy
        return self

    def max_crashes(self, max_crashes: int) -> "ActorModel":
        self._max_crashes = max_crashes
        return self

    def property(self, expectation, name: str = None, condition=None):
        """Builder-style with 3 args (expectation, name, condition); with a
        single string argument, behaves as ``Model.property`` name lookup."""
        if name is None and condition is None:
            return Model.property(self, expectation)
        self._properties.append(Property(expectation, name, condition))
        self._property_codec_pos.append(self._properties_added)
        self._properties_added += 1
        return self

    def retain_properties(self, *names: str) -> "ActorModel":
        """Keeps only the named properties (e.g. to benchmark
        time-to-counterexample on a single falsifiable liveness property —
        checkers finish early once every remaining property has a
        discovery). Packed codecs stay aligned: their positional
        ``packed_conditions`` list is filtered by the same positions."""
        if not names:
            raise ValueError(
                "retain_properties needs at least one property name "
                "(a checker with no properties explores nothing)"
            )
        have = {p.name for p in self._properties}
        missing = [n for n in names if n not in have]
        if missing:
            raise ValueError(f"unknown properties: {missing} (have {sorted(have)})")
        keep = [i for i, p in enumerate(self._properties) if p.name in names]
        self._properties = [self._properties[i] for i in keep]
        self._property_codec_pos = [self._property_codec_pos[i] for i in keep]
        return self

    def record_msg_in(self, fn) -> "ActorModel":
        """fn(cfg, history, envelope) -> new history or None (no change)."""
        self._record_msg_in = fn
        return self

    def record_msg_out(self, fn) -> "ActorModel":
        self._record_msg_out = fn
        return self

    def within_boundary_fn(self, fn) -> "ActorModel":
        self._within_boundary = fn
        return self

    # -- internals -----------------------------------------------------------

    def _process_commands(self, id: Id, out: Out, state: ActorModelState) -> None:
        """Applies an actor's output commands to the (freshly copied) system
        state: sends to the network (with history hook), timer bookkeeping."""
        index = int(id)
        for c in out.commands:
            if c.kind == SEND:
                dst, msg = c.args
                history = self._record_msg_out(
                    self.cfg, state.history, Envelope(src=id, dst=dst, msg=msg)
                )
                if history is not None:
                    state.history = history
                state.network.send(Envelope(src=id, dst=Id(dst), msg=msg))
            elif c.kind == SET_TIMER:
                timer, _duration = c.args
                while len(state.timers_set) <= index:
                    state.timers_set.append(Timers())
                state.timers_set[index].set(timer)
            elif c.kind == CANCEL_TIMER:
                (timer,) = c.args
                state.timers_set[index].cancel(timer)

    # -- Model interface -----------------------------------------------------

    def init_states(self) -> List[ActorModelState]:
        init_sys_state = ActorModelState(
            actor_states=[],
            history=self.init_history,
            timers_set=[Timers() for _ in self.actors_list],
            network=self._init_network.copy(),
            crashed=[False] * len(self.actors_list),
        )
        for index, actor in enumerate(self.actors_list):
            id = Id(index)
            out = Out()
            state = actor.on_start(id, out)
            init_sys_state.actor_states.append(state)
            self._process_commands(id, out, init_sys_state)
        return [init_sys_state]

    def actions(self, state: ActorModelState, actions: List) -> None:
        for env in state.network.iter_deliverable():
            # option 1: message is lost
            if self._lossy_network:
                actions.append(DropAction(env))
            # option 2: message is delivered (skip if recipient DNE; for
            # ordered networks iter_deliverable already yields flow heads only)
            if int(env.dst) < len(self.actors_list):
                actions.append(
                    DeliverAction(src=env.src, dst=env.dst, msg=env.msg)
                )
        # option 3: actor timeout
        for index, timers in enumerate(state.timers_set):
            for timer in timers:
                actions.append(TimeoutAction(Id(index), timer))
        # option 4: actor crash
        n_crashed = sum(1 for c in state.crashed if c)
        if n_crashed < self._max_crashes:
            for index, crashed in enumerate(state.crashed):
                if not crashed:
                    actions.append(CrashAction(Id(index)))

    def next_state(
        self, last_sys_state: ActorModelState, action
    ) -> Optional[ActorModelState]:
        if isinstance(action, DropAction):
            next_state = last_sys_state.copy()
            next_state.network.on_drop(action.envelope)
            return next_state

        if isinstance(action, DeliverAction):
            src, id, msg = action.src, action.dst, action.msg
            index = int(id)
            # Not all messages can be delivered, so ignore those.
            if index >= len(last_sys_state.actor_states):
                return None
            if last_sys_state.crashed[index]:
                return None
            last_actor_state = last_sys_state.actor_states[index]

            out = Out()
            returned = self.actors_list[index].on_msg(
                id, last_actor_state, src, msg, out
            )
            is_ordered = self._init_network.kind == ORDERED
            # Some operations are no-ops, so ignore those as well (but ordered
            # networks must still consume the message to preserve FIFO state).
            if is_no_op(returned, out) and not is_ordered:
                return None
            history = self._record_msg_in(
                self.cfg,
                last_sys_state.history,
                Envelope(src=src, dst=id, msg=msg),
            )

            next_sys_state = last_sys_state.copy()
            next_sys_state.network.on_deliver(Envelope(src=src, dst=id, msg=msg))
            if returned is not None:
                next_sys_state.actor_states[index] = returned
            if history is not None:
                next_sys_state.history = history
            self._process_commands(id, out, next_sys_state)
            return next_sys_state

        if isinstance(action, TimeoutAction):
            id, timer = action.id, action.timer
            index = int(id)
            out = Out()
            returned = self.actors_list[index].on_timeout(
                id, last_sys_state.actor_states[index], timer, out
            )
            if is_no_op_with_timer(returned, out, timer):
                return None
            next_sys_state = last_sys_state.copy()
            # The timer is no longer valid.
            next_sys_state.timers_set[index].cancel(timer)
            if returned is not None:
                next_sys_state.actor_states[index] = returned
            self._process_commands(id, out, next_sys_state)
            return next_sys_state

        if isinstance(action, CrashAction):
            index = int(action.id)
            next_sys_state = last_sys_state.copy()
            next_sys_state.timers_set[index].cancel_all()
            next_sys_state.crashed[index] = True
            return next_sys_state

        raise TypeError(f"unknown action: {action!r}")

    def properties(self) -> List[Property]:
        return list(self._properties)

    def within_boundary(self, state: ActorModelState) -> bool:
        return self._within_boundary(self.cfg, state)

    def format_action(self, action) -> str:
        if isinstance(action, DeliverAction):
            return f"{action.src!r} → {action.msg!r} → {action.dst!r}"
        return repr(action)

    def format_step(self, last_state, action) -> Optional[str]:
        next_state = self.next_state(last_state, action)
        return None if next_state is None else repr(next_state)

    def as_svg(self, path) -> Optional[str]:
        """Renders a sequence diagram of a path (for the Explorer UI).

        Reference: ``/root/reference/src/actor/model.rs:475-640``."""
        plot = lambda x, y: (x * 100, y * 30)
        actor_count = len(self.actors_list)
        path_vec = path.into_vec()
        height = 30 * (len(path_vec) + 1)
        width = 100 * (actor_count + 1)
        svg = [
            f'<svg version="1.1" baseProfile="full" width="{width}" '
            f'height="{height}" viewBox="-20 -20 {width + 20} {height + 20}" '
            'xmlns="http://www.w3.org/2000/svg">'
        ]
        # Vertical timeline per actor.
        for actor_index in range(actor_count):
            x1, y1 = plot(actor_index, 0)
            x2, y2 = plot(actor_index, len(path_vec))
            svg.append(f'<line x1="{x1}" y1="{y1}" x2="{x2}" y2="{y2}" class="svg-actor-timeline" />')
            svg.append(f'<text x="{x1}" y="{y1}" class="svg-actor-label">{actor_index}</text>')
        # Event markers per step.
        time = 0
        send_time_by_env = {}
        for state, action in path_vec:
            time += 1
            if isinstance(action, DeliverAction):
                x_to, y_to = plot(int(action.dst), time)
                env = Envelope(action.src, action.dst, action.msg)
                if env in send_time_by_env:
                    x_from, y_from = plot(int(action.src), send_time_by_env[env])
                    svg.append(
                        f'<line x1="{x_from}" x2="{x_to}" y1="{y_from}" y2="{y_to}" '
                        'marker-end="url(#arrow)" class="svg-event-line" />'
                    )
                svg.append(f'<circle cx="{x_to}" cy="{y_to}" r="10" class="svg-event-shape" />')
                svg.append(f'<text x="{x_to}" y="{y_to}" class="svg-event-label">{action.msg!r}</text>')
            elif isinstance(action, TimeoutAction):
                x, y = plot(int(action.id), time)
                svg.append(f'<rect x="{x - 10}" y="{y - 10}" width="20" height="20" class="svg-event-shape" />')
                svg.append(f'<text x="{x}" y="{y}" class="svg-event-label">Timeout</text>')
            # Track sends at this step by diffing network contents.
            if action is not None:
                next_state_obj = self.next_state(state, action)
                if next_state_obj is not None:
                    before = {}
                    for env in state.network.iter_all():
                        before[env] = before.get(env, 0) + 1
                    for env in next_state_obj.network.iter_all():
                        before[env] = before.get(env, 0) - 1
                    for env, count in before.items():
                        if count < 0:
                            send_time_by_env[env] = time
        svg.append("</svg>")
        return "".join(svg)
