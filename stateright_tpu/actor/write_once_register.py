"""Write-once-register protocol adapter: message interface + checking client.

Like the register adapter (``stateright_tpu.actor.register``) but for
write-once semantics: a ``PutFail`` response signals a rejected second write,
and the history hooks record ``WriteFail`` returns for the
``WORegister`` sequential spec.

Reference: ``/root/reference/src/actor/write_once_register.rs``. The
reference wraps servers in a ``WORegisterActor::Server`` variant purely for
Rust type unification; Python servers implement the message interface
directly, so only the client actor and history hooks are needed. Symmetry:
all message/state types here are plain dataclasses/tuples, which the
rewriter traverses structurally (the reference needs explicit ``Rewrite``
impls, ``:290-331``).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..semantics.write_once_register import (
    WO_READ,
    WO_WRITE_OK,
    WO_WRITE_FAIL,
    WoReadOk,
    WoWrite,
)
from .network import Envelope
from .register import (  # shared message shapes + client base
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
)


@dataclass(frozen=True)
class PutFail:
    """Indicates an unsuccessful ``Put`` (the register was already written)."""

    request_id: int

    def __repr__(self):
        return f"PutFail({self.request_id!r})"


# -- history hooks -----------------------------------------------------------


def record_invocations(_cfg, history, env: Envelope):
    """Pass to ``ActorModel.record_msg_out``: Read on Get, Write on Put."""
    if isinstance(env.msg, Get):
        h = history.clone()
        try:
            h.on_invoke(env.src, WO_READ)
        except ValueError:
            pass
        return h
    if isinstance(env.msg, Put):
        h = history.clone()
        try:
            h.on_invoke(env.src, WoWrite(env.msg.value))
        except ValueError:
            pass
        return h
    return None


def record_returns(_cfg, history, env: Envelope):
    """Pass to ``ActorModel.record_msg_in``: ReadOk on GetOk, WriteOk on
    PutOk, WriteFail on PutFail."""
    if isinstance(env.msg, GetOk):
        h = history.clone()
        # The spec's read result is an option: None (unset) | ("Some", v).
        option = None if env.msg.value is None else ("Some", env.msg.value)
        try:
            h.on_return(env.dst, WoReadOk(option))
        except ValueError:
            pass
        return h
    if isinstance(env.msg, PutOk):
        h = history.clone()
        try:
            h.on_return(env.dst, WO_WRITE_OK)
        except ValueError:
            pass
        return h
    if isinstance(env.msg, PutFail):
        h = history.clone()
        try:
            h.on_return(env.dst, WO_WRITE_FAIL)
        except ValueError:
            pass
        return h
    return None


# -- the model-checking client actor -----------------------------------------


class WORegisterClient(RegisterClient):
    """A ``RegisterClient`` whose Puts also complete on ``PutFail`` — a
    rejected write-once write still finishes the operation."""

    def name(self) -> str:
        return "WOClient"

    def _completes_put(self, msg) -> bool:
        return isinstance(msg, (PutOk, PutFail))


__all__ = [
    "Get",
    "GetOk",
    "Internal",
    "Put",
    "PutFail",
    "PutOk",
    "WORegisterClient",
    "record_invocations",
    "record_returns",
]
