"""Pluggable modeled-network semantics — the key state-space knob.

Three semantics (reference: ``Network`` at
``/root/reference/src/actor/network.rs:46-68``):

- ``unordered_duplicating``: messages race and can be redelivered (delivery is
  a no-op removal; only Drop removes forever). State: a set of envelopes.
- ``unordered_nonduplicating``: messages race, delivered at most once. State:
  a multiset (envelope -> count).
- ``ordered``: per directed actor pair, FIFO flows. State: (src, dst) -> queue.

In the packed TPU representation these become fixed-capacity envelope tables
with count columns / ring buffers (``stateright_tpu.models.packing``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Tuple

from .actor import Id

ORDERED = "ordered"
UNORDERED_DUPLICATING = "unordered_duplicating"
UNORDERED_NONDUPLICATING = "unordered_nonduplicating"


@dataclass(frozen=True)
class Envelope:
    """The source and destination for a message."""

    src: Id
    dst: Id
    msg: object

    def __repr__(self) -> str:
        return f"Envelope {{ src: {self.src!r}, dst: {self.dst!r}, msg: {self.msg!r} }}"


class Network:
    """A network of in-flight messages with selectable semantics."""

    def __init__(self, kind: str, data=None):
        self.kind = kind
        if data is not None:
            self.data = data
        elif kind == ORDERED:
            # (src, dst) -> list of msgs (FIFO). Iterated in sorted key order
            # (the reference uses a BTreeMap).
            self.data: Dict = {}
        else:
            # Envelope -> count. For duplicating networks counts are always 1
            # (set semantics); insertion order gives deterministic iteration.
            self.data = {}

    # -- constructors --------------------------------------------------------

    @staticmethod
    def new_ordered(envelopes=()) -> "Network":
        net = Network(ORDERED)
        for env in envelopes:
            net.send(env)
        return net

    @staticmethod
    def new_unordered_duplicating(envelopes=()) -> "Network":
        net = Network(UNORDERED_DUPLICATING)
        for env in envelopes:
            net.send(env)
        return net

    @staticmethod
    def new_unordered_nonduplicating(envelopes=()) -> "Network":
        net = Network(UNORDERED_NONDUPLICATING)
        for env in envelopes:
            net.send(env)
        return net

    @staticmethod
    def names() -> List[str]:
        return [ORDERED, UNORDERED_DUPLICATING, UNORDERED_NONDUPLICATING]

    @staticmethod
    def from_name(name: str) -> "Network":
        if name not in Network.names():
            raise ValueError(f"unable to parse network name: {name}")
        return Network(name)

    # -- queries -------------------------------------------------------------

    def iter_all(self) -> Iterator[Envelope]:
        """All envelopes, with multiplicity."""
        if self.kind == ORDERED:
            for (src, dst) in sorted(self.data):
                for msg in self.data[(src, dst)]:
                    yield Envelope(src, dst, msg)
        elif self.kind == UNORDERED_NONDUPLICATING:
            for env, count in self.data.items():
                for _ in range(count):
                    yield env
        else:
            yield from self.data

    def iter_deliverable(self) -> Iterator[Envelope]:
        """All distinct deliverable envelopes (flow heads for ordered)."""
        if self.kind == ORDERED:
            for (src, dst) in sorted(self.data):
                yield Envelope(src, dst, self.data[(src, dst)][0])
        else:
            yield from self.data

    def __len__(self) -> int:
        if self.kind == ORDERED:
            return sum(len(q) for q in self.data.values())
        if self.kind == UNORDERED_NONDUPLICATING:
            return sum(self.data.values())
        return len(self.data)

    # -- mutations (on freshly copied states only) ---------------------------

    def send(self, envelope: Envelope) -> None:
        if self.kind == ORDERED:
            self.data.setdefault((envelope.src, envelope.dst), []).append(
                envelope.msg
            )
        elif self.kind == UNORDERED_NONDUPLICATING:
            self.data[envelope] = self.data.get(envelope, 0) + 1
        else:
            self.data.setdefault(envelope, True)

    def on_deliver(self, envelope: Envelope) -> None:
        if self.kind == UNORDERED_DUPLICATING:
            return  # no-op: the message can be redelivered
        self._remove(envelope)

    def on_drop(self, envelope: Envelope) -> None:
        if self.kind == UNORDERED_DUPLICATING:
            self.data.pop(envelope, None)
            return
        self._remove(envelope)

    def _remove(self, envelope: Envelope) -> None:
        if self.kind == ORDERED:
            key = (envelope.src, envelope.dst)
            flow = self.data.get(key)
            if flow is None:
                raise KeyError(
                    f"flow not found. src={envelope.src!r}, dst={envelope.dst!r}"
                )
            flow.remove(envelope.msg)  # raises ValueError if missing
            if not flow:
                del self.data[key]  # canonical: no empty flows
        else:
            count = self.data.get(envelope)
            if count is None:
                raise KeyError("envelope not found")
            if count == 1:
                del self.data[envelope]
            else:
                self.data[envelope] = count - 1

    # -- value semantics -----------------------------------------------------

    def copy(self) -> "Network":
        if self.kind == ORDERED:
            return Network(self.kind, {k: list(v) for k, v in self.data.items()})
        return Network(self.kind, dict(self.data))

    def __stable_fields__(self):
        if self.kind == ORDERED:
            return (
                self.kind,
                tuple(
                    (k, tuple(v)) for k, v in sorted(self.data.items())
                ),
            )
        # Order-insensitive: hash as a dict (envelope -> count / True).
        return (self.kind, dict(self.data))

    def __eq__(self, other) -> bool:
        if not isinstance(other, Network) or self.kind != other.kind:
            return False
        return self.data == other.data

    def __hash__(self) -> int:
        from ..core.fingerprint import stable_hash

        return stable_hash(self.__stable_fields__())

    def __repr__(self) -> str:
        return f"Network::{self.kind}({list(self.iter_all())!r})"
