"""Ordered reliable link (ORL): exactly-once in-order delivery over lossy nets.

Wraps any actor with sequence numbers, acknowledgements, and a periodic
resend timer — the "perfect link" construction (Cachin/Guerraoui/Rodrigues)
plus per-source/destination-pair ordering. Pair with
``Network.new_ordered`` to shrink the checked state space.

Semantics per the reference (``/root/reference/src/actor/ordered_reliable_link.rs``):
send side tracks unacked messages (resent on the network timer); the receive
side acks every Deliver and drops already-delivered sequence numbers; actor
restarts are not supported (sequencers are not persisted). Deviation: the
reference ``todo!()``s SetTimer/CancelTimer from the wrapped actor
(``:191-196``); here user timers are forwarded through a timer wrapper.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

from .actor import (
    CANCEL_TIMER,
    SEND,
    SET_TIMER,
    Actor,
    Id,
    Out,
    is_no_op,
)

# Messages: ("Deliver", seq, inner_msg) | ("Ack", seq)
# Timers:   ("Network",) | ("User", inner_timer)
NETWORK_TIMER = ("Network",)


def deliver_msg(seq: int, msg) -> Tuple:
    return ("Deliver", seq, msg)


def ack_msg(seq: int) -> Tuple:
    return ("Ack", seq)


def user_timer(timer) -> Tuple:
    return ("User", timer)


@dataclass(frozen=True)
class OrlState:
    # send side
    next_send_seq: int
    msgs_pending_ack: Tuple  # sorted tuple of (seq, dst, msg)
    # receive side
    last_delivered_seqs: Tuple  # sorted tuple of (src, seq)
    wrapped_state: object


class ActorWrapper(Actor):
    """Wraps an actor with logic to (1) maintain message order, (2) resend
    lost messages, and (3) avoid redelivery."""

    def __init__(self, wrapped_actor: Actor, resend_interval=(1.0, 2.0)):
        self.wrapped_actor = wrapped_actor
        self.resend_interval = resend_interval

    def name(self) -> str:
        return self.wrapped_actor.name()

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _last_delivered(state: OrlState, src: Id) -> int:
        for s, seq in state.last_delivered_seqs:
            if s == src:
                return seq
        return 0

    def _process_output(self, seq, pending, wrapped_out: Out, o: Out):
        """Translates the wrapped actor's commands; returns updated
        (next_send_seq, msgs_pending_ack)."""
        pending = list(pending)
        for command in wrapped_out:
            if command.kind == SEND:
                dst, inner = command.args
                o.send(dst, deliver_msg(seq, inner))
                pending.append((seq, dst, inner))
                seq += 1
            elif command.kind == SET_TIMER:
                timer, duration = command.args
                o.set_timer(user_timer(timer), duration)
            elif command.kind == CANCEL_TIMER:
                o.cancel_timer(user_timer(command.args[0]))
        return seq, tuple(sorted(pending, key=lambda p: p[0]))

    # -- Actor surface -----------------------------------------------------

    def on_start(self, id: Id, o: Out) -> OrlState:
        o.set_timer(NETWORK_TIMER, self.resend_interval)
        wrapped_out = Out()
        wrapped_state = self.wrapped_actor.on_start(id, wrapped_out)
        seq, pending = self._process_output(1, (), wrapped_out, o)
        return OrlState(
            next_send_seq=seq,
            msgs_pending_ack=pending,
            last_delivered_seqs=(),
            wrapped_state=wrapped_state,
        )

    def on_msg(self, id: Id, state: OrlState, src: Id, msg, o: Out):
        kind = msg[0]
        if kind == "Deliver":
            _, seq, inner = msg
            # Always ack to stop resends; drop if already delivered.
            o.send(src, ack_msg(seq))
            if seq <= self._last_delivered(state, src):
                return None
            wrapped_out = Out()
            next_wrapped = self.wrapped_actor.on_msg(
                id, state.wrapped_state, src, inner, wrapped_out
            )
            if is_no_op(next_wrapped, wrapped_out):
                return None
            next_seq, pending = self._process_output(
                state.next_send_seq, state.msgs_pending_ack, wrapped_out, o
            )
            delivered = tuple(
                sorted(
                    [(s, q) for s, q in state.last_delivered_seqs if s != src]
                    + [(src, seq)]
                )
            )
            return OrlState(
                next_send_seq=next_seq,
                msgs_pending_ack=pending,
                last_delivered_seqs=delivered,
                wrapped_state=(
                    next_wrapped
                    if next_wrapped is not None
                    else state.wrapped_state
                ),
            )
        if kind == "Ack":
            _, seq = msg
            pending = tuple(
                p for p in state.msgs_pending_ack if p[0] != seq
            )
            if pending == state.msgs_pending_ack:
                return None
            return OrlState(
                next_send_seq=state.next_send_seq,
                msgs_pending_ack=pending,
                last_delivered_seqs=state.last_delivered_seqs,
                wrapped_state=state.wrapped_state,
            )
        return None

    def on_timeout(self, id: Id, state: OrlState, timer, o: Out):
        if timer == NETWORK_TIMER:
            o.set_timer(NETWORK_TIMER, self.resend_interval)
            for seq, dst, inner in state.msgs_pending_ack:
                o.send(dst, deliver_msg(seq, inner))
            return None
        if timer[0] == "User":
            wrapped_out = Out()
            next_wrapped = self.wrapped_actor.on_timeout(
                id, state.wrapped_state, timer[1], wrapped_out
            )
            if is_no_op(next_wrapped, wrapped_out):
                return None
            next_seq, pending = self._process_output(
                state.next_send_seq, state.msgs_pending_ack, wrapped_out, o
            )
            return OrlState(
                next_send_seq=next_seq,
                msgs_pending_ack=pending,
                last_delivered_seqs=state.last_delivered_seqs,
                wrapped_state=(
                    next_wrapped
                    if next_wrapped is not None
                    else state.wrapped_state
                ),
            )
        return None
